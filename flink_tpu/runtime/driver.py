"""The per-host driver loop — the StreamTask/mailbox analogue.

ref: streaming/runtime/tasks/{StreamTask,OneInputStreamTask}.java and
tasks/mailbox/MailboxProcessor.runMailboxLoop — the reference's
single-threaded event loop where the default action processes input and
control actions (checkpoints, timers) interleave as mails.

TPU-first redesign: the loop's unit is a **microbatch**, not a record.
One iteration = pull a batch from a source, run the fused host ingest
chain, fold it into the stateful ops' device state, advance the
watermark clock, and hand fired windows to downstream nodes/sinks.
Control actions (checkpoint snapshots) happen between iterations — a
step boundary is a global barrier (SURVEY §6.4), which is what makes
exactly-once cheap here.
"""
from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.config import (
    CheckpointingOptions,
    ClusterOptions,
    Configuration,
    PipelineOptions,
    StateOptions,
)
from flink_tpu.graph.compiler import (
    STAGE_HEAD_KINDS,
    ExecNode,
    ExecutionPlan,
)
from flink_tpu.time.watermarks import LONG_MIN, WatermarkTracker, make_generator

Batch = Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]  # data, ts, valid


class JobCancelledError(RuntimeError):
    """Raised inside the run loop when the job's cancel flag is set —
    the cooperative cancellation point (ref: Task.cancelExecution /
    StreamTask cancellation). The run() cleanup path treats it like any
    abort: drain discarded, sinks' uncommitted output dropped."""


class Driver:
    """Single-process execution of a lowered plan (the LocalExecutor /
    MiniCluster path; multi-host runs the same loop per host runner under
    the coordinator, ref: runtime/minicluster/MiniCluster.java)."""

    def __init__(self, plan: ExecutionPlan, config: Configuration,
                 mesh_plan: Optional[Any] = None):
        self.plan = plan
        self.config = config
        self.mesh_plan = mesh_plan
        # submit-time plan analysis results (execute() refreshes this;
        # an empty list before/without analysis keeps the surface total)
        self.analysis_findings: List[Any] = []
        self._upstream: Dict[int, List[int]] = {nid: [] for nid in plan.nodes}
        for n in plan.nodes.values():
            for d in n.downstream:
                self._upstream[d].append(n.id)
        self._ops: Dict[int, Any] = {}
        self._partitioners: Dict[int, Any] = {}
        self._out_wm: Dict[int, int] = {nid: LONG_MIN for nid in plan.nodes}
        self._wm_gens: Dict[int, Any] = {}
        self._max_ts: Dict[int, int] = {}
        self.metrics: Dict[str, int] = {
            "records_in": 0, "records_out": 0, "batches": 0, "fired_windows": 0,
        }
        from flink_tpu.obs.metrics import MetricRegistry

        # ref: TaskIOMetricGroup numRecordsIn/Out + latency markers (§6.1)
        self.registry = MetricRegistry()
        g = self.registry.group("driver")
        g.gauge("records_in", lambda: self.metrics["records_in"])
        g.gauge("records_out", lambda: self.metrics["records_out"])
        g.gauge("fired_windows", lambda: self.metrics["fired_windows"])
        # loss counters — directory-full drops and exchange overflow must
        # be observable live, not just at job end
        g.gauge("records_dropped_full", lambda: sum(
            getattr(op, "records_dropped_full", 0)
            for op in self._ops.values()))
        g.gauge("exchange_overflow", lambda: sum(
            getattr(op, "exchange_overflow", 0)
            for op in self._ops.values()))
        self._eps_meter = g.meter("records_per_sec")
        # FIRE→SINK latency, not ingest→sink: the clock starts when the
        # watermark advance DISPATCHES a fired window (see
        # _emit_fired_sync) and stops at sink delivery — the
        # latency-marker analogue (LatencyMarker.java). Time a record
        # spends queued before its step dispatches is NOT included;
        # artifacts quoting this metric must say "fire→sink", never
        # "end-to-end" (VERDICT r05 weak #3; BASELINE.md states the
        # same).
        self._lat_hist = g.histogram("emit_latency_ms")
        self._wm_lag = g.gauge("watermark_lag_ms")
        # adaptive microbatch debloater (ref: BufferDebloater): when a
        # latency target is set, ingest re-chunks source batches; the
        # chunk halves while recent emit p99 overshoots the target and
        # regrows while it sits under half of it
        from flink_tpu.config import PipelineOptions as _PO

        self._debloat_target = float(config.get(_PO.TARGET_LATENCY))
        self._debloat_chunk: Optional[int] = None
        self._debloat_min = 4096
        self._debloat_seen = 0  # histogram count at last control step
        # sub-batch fire/emit decoupling (PROFILE.md §8.6): K > 1 runs
        # each logical batch as K chained sub-batch device steps with
        # watermark advances + fire dispatches interleaved at sub-batch
        # boundaries, so fired rows become host-visible at ~batch_wall/K
        # cadence. Source positions / throttle probes / checkpoint
        # checks stay at logical-batch granularity. K=1 IS the exact
        # pre-split path (every new branch is guarded on K > 1).
        self._sub_batches = int(config.get(_PO.SUB_BATCHES))
        if self._sub_batches < 1:
            raise ValueError(
                f"pipeline.sub-batches must be >= 1, got "
                f"{self._sub_batches}")
        mb = int(config.get(_PO.MICROBATCH_SIZE))
        if mb % self._sub_batches:
            raise ValueError(
                f"pipeline.sub-batches ({self._sub_batches}) must "
                f"divide pipeline.microbatch-size ({mb}) — sub-batches "
                "are equal slices of the logical batch (the plan "
                "analyzer flags this at submit: SUBBATCH_INVALID)")
        # per-source sub-batch factor actually in effect this run:
        # sub_batches for device-chained sources iterating a subdivided
        # stream (positions then count SUB-batches), 1 otherwise (host
        # path slices inside one position). Snapshots record it so a
        # restore under a different factor can re-base positions.
        self._sub_factor: Dict[int, int] = {}
        g.gauge("debloat_chunk",
                lambda: float(self._debloat_chunk or 0))
        # per-phase wall-time accumulators (seconds) for the ingest loop
        # and drain thread — merged into JobResult as profile.* so perf
        # work is steered by measurement (PROFILE.md), not vibes
        self.prof: Dict[str, float] = collections.defaultdict(float)
        self._emit_q = None
        self._profiler = None  # armed per run (pipeline.profile-dir)
        self._drain_error: Optional[BaseException] = None
        # per-run discard cell: set on abort so the run's drain thread
        # drops (never delivers) everything it still holds. One CELL per
        # run — an abandoned (wedged, timed-out) drain keeps its own
        # permanently-set cell, so it can never deliver into, nor be
        # re-armed by, a later run on the same Driver.
        self._drain_discard = [False]
        self._stateless_cache: Dict[int, bool] = {}
        # batch (bounded) mode: open blocking-edge writers, keyed by
        # (from_node, to_node); _push diverts matching edges into the
        # shuffle spool instead of the consumer. Always a dict (empty
        # on the streaming path) so the hot-path check is one truth test.
        self._batch_capture: Dict[Tuple[int, int], Any] = {}
        import threading

        # set while a barrier (checkpoint / end-of-input) is waiting on
        # the emit queue: overrides the drain deferral immediately
        self._flush_req = threading.Event()
        # Link-quiet handshake: device→host fetches starve while
        # host→device ingest traffic flows (measured: a concurrent fetch
        # NEVER completes under continuous h2d+dispatch on a
        # remote-attached chip). The drain holds this lock during its
        # fetch; the ingest loop acquires it once per batch boundary —
        # so a pending fetch gets a quiet link within one batch, and
        # ingest resumes the moment the fetch lands.
        self._link_lock = threading.Lock()
        defer = self.config.get(PipelineOptions.EMIT_DEFER_MS)
        if defer < 0:
            import jax

            # accelerator default 10ms: periodic polls read only
            # ANNOUNCED-and-landed ring versions (drain_ring min_no=0),
            # so a poll can never park behind in-flight compute — the
            # deferral only sets the emit-latency floor (p50 ≈ defer/2
            # + decode). Measured on-chip (round 4): defer 10ms beats
            # 100ms on BOTH axes — 9.0M vs 8.2M ev/s, p50 36ms vs
            # 101ms, p99 154ms vs 283ms.
            defer = 0 if jax.default_backend() == "cpu" else 10
        self._emit_defer_s = defer / 1000.0

        # serializes downstream pushes from the ingest thread and the
        # drain thread (shared sinks + metrics are single-writer at a
        # time; the expensive materialization stays outside the lock)
        self._push_lock = threading.Lock()
        # fair drain scheduling (session-cluster mode): co-resident
        # jobs' drain fetches take round-robin turns on the process-
        # global gate so one tenant's fire burst cannot starve a
        # peer's emit ring on the shared device→host link. Off (None)
        # outside session deploys — the single-job path is untouched.
        from flink_tpu.config import SessionOptions as _SO

        self._drain_gate = None
        self._gate_token = f"drv-{id(self)}"
        if bool(self.config.get(_SO.FAIR_DRAIN)):
            from flink_tpu.runtime.session import drain_gate

            self._drain_gate = drain_gate()
        self._build_ops()
        # plan-time HBM budgeting: dense static layouts make the device
        # footprint computable BEFORE the first step — fail at build
        # with a breakdown, not mid-run in the XLA allocator (ref:
        # MemoryManager managed-memory budgets; memory.hbm-budget)
        from flink_tpu.config import MemoryOptions
        from flink_tpu.memory import MemoryBudget

        self.memory = MemoryBudget(int(config.get(MemoryOptions.HBM_BUDGET)))
        for nid, op in self._ops.items():
            if hasattr(op, "hbm_bytes"):
                n = self.plan.node(nid)
                self.memory.register(
                    f"{n.kind}:{n.name or nid}", op.hbm_bytes(),
                    detail=f"layout={getattr(op, 'layout', None)}")
        self.memory.check()
        g2 = self.registry.group("memory")
        g2.gauge("hbm_state_bytes", lambda: float(self.memory.hbm_total))
        g2.gauge("host_spill_bytes", lambda: float(sum(
            getattr(getattr(op, "_spill", None), "bytes_used", lambda: 0)()
            for op in self._ops.values()
            if getattr(op, "_spill", None) is not None)))

    # -- construction ----------------------------------------------------
    def _build_ops(self) -> None:
        num_shards = self.config.get(StateOptions.NUM_KEY_SHARDS)
        slots = self.config.get(StateOptions.SLOTS_PER_SHARD)
        self._base_inflight = int(
            self.config.get(PipelineOptions.MAX_INFLIGHT_STEPS))
        # session resource shares (runtime/session.py): the dispatcher
        # stamps session.concurrent-jobs = K (the STATIC slot-
        # proportional denominator: jobs of this quota that fit one
        # runner) into the deploy config; this job's in-flight step
        # credit and host-pool worker count each take a 1/K share so
        # co-resident jobs cannot oversubscribe the transport queue or
        # the host cores, regardless of deploy order — the host-pool /
        # in-flight legs of the admission quota. K = 1 (every
        # non-session run) changes nothing.
        from flink_tpu.config import SessionOptions

        self._session_share = max(
            1, int(self.config.get(SessionOptions.CONCURRENT_JOBS)))
        if self._session_share > 1:
            self._base_inflight = max(
                1, self._base_inflight // self._session_share)
        # sub-batching dispatches K steps per logical batch, each 1/K
        # the records: scale the in-flight credit so pipeline depth
        # measured in LOGICAL batches (and therefore in bytes queued on
        # the transport) is unchanged — emit polls read only landed
        # ring copies, so the deeper sub-step queue never parks a drain
        # behind in-flight compute. A device chain whose source cannot
        # subdivide still steps at LOGICAL granularity; its operator is
        # reset to the base credit at chain attach (the scaled credit
        # there would queue K× the bytes, not the same bytes).
        inflight = self._base_inflight * self._sub_batches
        # control-plane knobs (PROFILE.md §12): fire-gated dispatch and
        # the readiness mechanism the throttle uses. Validated here so a
        # typo fails at build, not deep inside the first throttle.
        self._fire_gate = bool(self.config.get(PipelineOptions.FIRE_GATE))
        self._readiness = str(
            self.config.get(PipelineOptions.READINESS)).strip().lower()
        if self._readiness not in ("piggyback", "probe"):
            raise ValueError(
                f"pipeline.readiness must be 'piggyback' or 'probe', "
                f"got {self._readiness!r} (the plan analyzer flags this "
                "at submit: READINESS_INVALID)")
        xcap = self.config.get(PipelineOptions.EXCHANGE_CAPACITY)
        if xcap < 0:
            raise ValueError(
                f"pipeline.exchange-capacity must be >= 0 (0 = auto), "
                f"got {xcap}")
        xcap = xcap or None
        backend = self.config.get(StateOptions.BACKEND)
        if backend not in ("hbm", "spill", "lsm"):
            raise ValueError(
                f"state.backend must be 'hbm', 'spill' or 'lsm', "
                f"got {backend!r}")
        # pane-ring sizing must cover the worst watermark lag of ANY
        # source feeding the job (per-source strategies override the
        # plan default)
        ooos = [self.plan.watermark_strategy.max_out_of_orderness_ms]
        for n in self.plan.nodes.values():
            if n.kind == "source" and n.watermark_strategy is not None:
                ooos.append(n.watermark_strategy.max_out_of_orderness_ms)
        wm = dataclasses.replace(self.plan.watermark_strategy,
                                 max_out_of_orderness_ms=max(ooos))
        # operator factory SPI (ref: OneInputStreamOperatorFactory): a
        # registered factory for a kind owns its construction — the
        # built-in window operator goes through its own registered
        # factory, third parties override by registering theirs
        from flink_tpu.ops.factory import (
            OperatorBuildContext,
            lookup_operator_factory,
        )

        # cross-host jobs: each process owns a contiguous shard span
        # (records arrive pre-routed through the DCN exchange)
        shard_range = None
        nproc = int(self.config.get(ClusterOptions.NUM_PROCESSES))
        if nproc > 1:
            pid = int(self.config.get(ClusterOptions.PROCESS_ID))
            spp = num_shards // nproc
            shard_range = (pid * spp, (pid + 1) * spp)
        # ONE shared host worker pool per driver (PROFILE §9, flink_tpu/
        # parallel/hostpool.py): sized by host.parallelism, handed to
        # every operator with host-resident parallel work; parallelism 1
        # creates no threads and keeps the exact serial paths
        from flink_tpu.config import HostOptions
        from flink_tpu.parallel.hostpool import HostPool

        host_w = int(self.config.get(HostOptions.PARALLELISM))
        if self._session_share > 1:
            # the host-pool share of the session quota: K co-resident
            # jobs split the configured worker count instead of each
            # claiming all of it
            host_w = max(1, host_w // self._session_share)
        self.host_pool = HostPool(host_w, registry=self.registry)
        fold_chunk = int(self.config.get(HostOptions.FOLD_CHUNK_RECORDS))
        if fold_chunk < 1:
            raise ValueError(
                f"host.fold-chunk-records must be >= 1, got {fold_chunk}")
        ctx = OperatorBuildContext(
            config=self.config, mesh_plan=self.mesh_plan,
            num_shards=num_shards, slots_per_shard=slots,
            max_inflight_steps=inflight, exchange_capacity=xcap,
            backend=backend,
            exchange_impl=self.config.get(ClusterOptions.EXCHANGE_IMPL),
            max_out_of_orderness_ms=wm.max_out_of_orderness_ms,
            shard_range=shard_range,
            host_pool=self.host_pool,
            fold_chunk_records=fold_chunk,
            fire_gate=self._fire_gate,
            readiness=self._readiness,
            memory_budget_bytes=int(
                self.config.get(StateOptions.MEMORY_BUDGET_BYTES)),
            lsm_dir=str(self.config.get(StateOptions.LSM_DIR)),
            lsm_compact_min_runs=int(
                self.config.get(StateOptions.LSM_COMPACT_MIN_RUNS)),
        )
        allow_drops = bool(self.config.get(StateOptions.ALLOW_DROPS))
        for n in self.plan.nodes.values():
            factory = lookup_operator_factory(n.kind)
            if factory is not None:
                self._ops[n.id] = factory(n, ctx)
            elif n.kind == "async_io":
                from flink_tpu.ops.async_io import AsyncIOOperator

                t = n.window_transform
                fn = t.fn
                call = (fn.invoke_batch
                        if hasattr(fn, "invoke_batch") else fn)
                self._ops[n.id] = AsyncIOOperator(
                    call, capacity=t.capacity, timeout_ms=t.timeout_ms,
                    ordered=t.ordered)
            elif n.kind == "cep":
                from flink_tpu.cep import CepOperator

                t = n.window_transform
                self._ops[n.id] = CepOperator(
                    t.pattern, num_shards=num_shards,
                    slots_per_shard=slots)
            elif n.kind == "process":
                from flink_tpu.ops.process import KeyedProcessOperator

                t = n.window_transform
                self._ops[n.id] = KeyedProcessOperator(
                    t.fn, num_shards=num_shards, slots_per_shard=slots)
            elif n.kind == "window_all":
                from flink_tpu.ops.window_all import WindowAllOperator

                t = n.window_transform
                self._ops[n.id] = WindowAllOperator(
                    t.assigner, t.aggregate,
                    allowed_lateness_ms=t.allowed_lateness_ms,
                    max_out_of_orderness_ms=max(wm.max_out_of_orderness_ms, 0),
                    host_pool=self.host_pool,
                    fold_chunk_records=fold_chunk)
            elif n.kind == "count_window":
                from flink_tpu.ops.count_window import CountWindowOperator

                if self.mesh_plan is not None:
                    raise NotImplementedError(
                        "count windows on a device mesh are not yet "
                        "supported; run without cluster.mesh-devices")
                t = n.window_transform
                self._ops[n.id] = CountWindowOperator(
                    t.aggregate, t.size, purge=t.purge,
                    num_shards=num_shards, slots_per_shard=slots)
            elif n.kind == "global_agg":
                from flink_tpu.ops.global_agg import GlobalAggregateOperator

                if self.mesh_plan is not None:
                    raise NotImplementedError(
                        "unwindowed aggregation on a device mesh is not "
                        "yet supported; run without cluster.mesh-devices")
                t = n.window_transform
                self._ops[n.id] = GlobalAggregateOperator(
                    t.aggregate, num_shards=num_shards,
                    slots_per_shard=slots,
                    retract=getattr(t, "retract", False))
            elif n.kind == "session":
                from flink_tpu.ops.session import SessionOperator

                t = n.window_transform
                self._ops[n.id] = SessionOperator(
                    gap_ms=t.gap_ms, agg=t.aggregate,
                    allowed_lateness_ms=t.allowed_lateness_ms,
                    num_shards=num_shards, slots_per_shard=slots,
                    max_out_of_orderness_ms=max(wm.max_out_of_orderness_ms, 0),
                    host_pool=self.host_pool,
                    retract=getattr(t, "retract", False),
                )
            elif n.kind == "evicting_window":
                from flink_tpu.ops.evicting_window import (
                    EvictingWindowOperator)

                t = n.window_transform
                self._ops[n.id] = EvictingWindowOperator(
                    t.assigner, t.window_fn, trigger=t.trigger,
                    evictor=t.evictor,
                    allowed_lateness_ms=t.allowed_lateness_ms)
            elif n.kind == "broadcast_connect":
                from flink_tpu.ops.broadcast import BroadcastConnectOperator

                self._ops[n.id] = BroadcastConnectOperator(
                    n.window_transform.fn)
            elif n.kind == "join":
                from flink_tpu.ops.join import WindowJoinOperator

                t = n.window_transform
                self._ops[n.id] = WindowJoinOperator(
                    t.assigner,
                    left_fields=t.left_fields, right_fields=t.right_fields,
                    num_shards=num_shards, slots_per_shard=slots,
                    max_out_of_orderness_ms=max(wm.max_out_of_orderness_ms, 0),
                    mode=getattr(t, "mode", "pairs"),
                )
        # default-safe state policy: full-directory drops FAIL the job
        # unless explicitly allowed (see state.keyed.account_full_drop)
        for op in self._ops.values():
            op.allow_drops = allow_drops

    # -- checkpointing ---------------------------------------------------
    def _setup_checkpointing(self, job_name: str):
        from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        interval = self.config.get(CheckpointingOptions.INTERVAL)
        restore = self.config.get(CheckpointingOptions.RESTORE)
        if interval <= 0 and not restore:
            return None
        nproc = int(self.config.get(ClusterOptions.NUM_PROCESSES))
        if nproc > 1:
            # cross-host jobs: each process snapshots ITS shard span
            # under its own directory; the ids align because the
            # checkpoint decision rides the step rendezvous
            pid = int(self.config.get(ClusterOptions.PROCESS_ID))
            job_name = f"{job_name}-p{pid}"
        storage = FsCheckpointStorage(
            self.config.get(CheckpointingOptions.DIRECTORY),
            job_id=job_name.replace("/", "_"),
            retained=self.config.get(CheckpointingOptions.RETAINED),
            compression=self.config.get(CheckpointingOptions.COMPRESSION),
            # coordinator-deployed attempts fence storage writes on the
            # attempt epoch: a deposed attempt's in-flight persist must
            # not clobber its successor's checkpoints (see
            # FsCheckpointStorage._check_fence); 0 = local unfenced
            epoch=int(self.config.get_raw("cluster.attempt", 0)))
        return CheckpointCoordinator(storage)

    def _snapshot(self, allow_reuse: bool = True) -> Dict[str, Any]:
        from flink_tpu.checkpoint.storage import ReusedOpState

        # incremental reuse (RocksDB shared-SST analogue): an operator
        # whose state_version is unchanged since the base (last
        # completed) checkpoint hardlinks that checkpoint's blob instead
        # of re-serializing. Savepoints stay self-contained.
        base = (self._ckpt_base
                if allow_reuse
                and self.config.get(CheckpointingOptions.INCREMENTAL)
                else None)
        ops: Dict[Any, Any] = {}
        versions: Dict[str, int] = {}
        for nid, op in self._ops.items():
            v = getattr(op, "state_version", None)
            versions[str(nid)] = -1 if v is None else int(v)
            if (v is not None and base is not None
                    and base["versions"].get(nid) == v
                    and nid in base["files"]):
                ops[nid] = ReusedOpState(
                    base["files"][nid], int(v),
                    # changelog aux (lsm runs) re-links from the BASE
                    # checkpoint's own hardlinks, never the store's
                    # live files — reuse must survive store compaction
                    aux=(base.get("aux") or {}).get(nid))
            else:
                ops[nid] = op.snapshot_state()
        self._last_freeze_versions = {
            nid: getattr(op, "state_version", -1)
            for nid, op in self._ops.items()}
        return {
            "sources": {sid: dict(pos) for sid, pos in self._positions.items()},
            # the sub-batch factor positions were counted under (device
            # chains iterate a subdivided stream): restore re-bases
            # positions when the factor differs — see _run_loop
            "sub_factors": dict(self._sub_factor),
            "wm_gens": {sid: [g.snapshot() for g in gens]
                        for sid, gens in self._wm_gens.items()},
            "max_ts": dict(self._max_ts),
            "out_wm": dict(self._out_wm),
            "operators": ops,
            "op_versions": versions,
            "partitioners": {nid: p.snapshot()
                             for nid, p in self._partitioners.items()},
            # staged-but-uncommitted 2PC sink epochs (prepare ran before
            # this snapshot, so the in-flight epoch is included) — the
            # TwoPhaseCommitSinkFunction pending-transaction-in-state rule
            "sinks": {
                nid: staged
                for nid, n in self.plan.nodes.items()
                if n.kind == "sink"
                and (staged := n.sink.snapshot_staged()) is not None
            },
            "metrics": dict(self.metrics),
            # key-group identity of the writing process: restore checks
            # it against the restoring process's shape and routes a
            # mismatch through checkpoint/repartition.py (the
            # StateAssignmentOperation role — see _load_repartitioned)
            "rescale": self._rescale_identity(),
        }

    def _rescale_identity(self) -> Dict[str, Any]:
        nproc = int(self.config.get(ClusterOptions.NUM_PROCESSES))
        pid = (int(self.config.get(ClusterOptions.PROCESS_ID))
               if nproc > 1 else 0)
        num_shards = int(self.config.get(StateOptions.NUM_KEY_SHARDS))
        spp = num_shards // max(nproc, 1)
        return {"nproc": nproc, "pid": pid, "num_shards": num_shards,
                "shard_range": [pid * spp, (pid + 1) * spp]}

    def _restore(self, payload: Dict[str, Any]) -> None:
        self._positions = {sid: dict(pos)
                           for sid, pos in payload["sources"].items()}
        self._restored_sub_factors = {
            int(k): int(v)
            for k, v in payload.get("sub_factors", {}).items()}
        # time-state keys may be absent: a state-processor savepoint
        # with reset_watermarks() restarts event time from scratch
        for sid, states in payload.get("wm_gens", {}).items():
            for g, s in zip(self._wm_gens[sid], states):
                g.restore(s)
        self._max_ts.update(payload.get("max_ts", {}))
        self._out_wm.update(payload.get("out_wm", {}))
        for nid, snap in payload["operators"].items():
            self._ops[nid].restore_state(snap)
        from flink_tpu.exchange.partitioners import make_partitioner

        for nid, psnap in payload.get("partitioners", {}).items():
            n = self.plan.node(nid)
            p = make_partitioner(n.partition_strategy, seed=nid)
            p.restore(psnap)
            self._partitioners[nid] = p
        # v2 incremental restore: adopt the checkpoint's per-op state
        # versions and make it the reuse base — an operator untouched
        # after restore hardlinks its blob at the very next checkpoint
        file_versions = payload.get("op_file_versions")
        # blob reuse keeps the ORIGINAL bytes; if the restored
        # checkpoint was written with a different compression than this
        # run's, hardlinking its blobs under the new manifest would make
        # later checkpoints undecodable — skip seeding the base
        if (file_versions and payload.get("op_file_compression", "none")
                != self.config.get(CheckpointingOptions.COMPRESSION)):
            file_versions = None
        if file_versions:
            for nid, v in file_versions.items():
                if nid in self._ops and hasattr(
                        self._ops[nid], "state_version"):
                    self._ops[nid].state_version = v
            self._ckpt_base = {
                "files": dict(payload.get("op_files", {})),
                "versions": dict(file_versions),
                "aux": {nid: dict(m) for nid, m in
                        (payload.get("op_aux_paths") or {}).items()},
            }
        self.metrics.update(payload["metrics"])
        staged_sinks = payload.get("sinks", {})
        cid = int(payload.get("checkpoint_id", 0))
        for nid, n in self.plan.nodes.items():
            if n.kind != "sink":
                continue
            if nid in staged_sinks:
                # re-commit epochs the completed checkpoint covers; a crash
                # between manifest write and commit must not lose them
                n.sink.restore_staged(staged_sinks[nid], cid)
            elif hasattr(n.sink, "abort_uncommitted"):
                n.sink.abort_uncommitted()

    def _abort_sinks(self) -> None:
        """Drop every sink's pending (never-committed) rows — the failed
        or superseded attempt's output must not leak into a later
        attempt that reuses the sink instances."""
        for n in self.plan.nodes.values():
            if n.kind == "sink" and hasattr(n.sink, "abort_uncommitted"):
                n.sink.abort_uncommitted()

    # -- rescale restore -------------------------------------------------
    def _rescale_from_paths(self) -> List[str]:
        """The savepoint set (one per OLD process, pid order) the last
        rescale redeploy restored from — injected by the coordinator as
        cluster.rescale-from so EVERY later attempt, not just the first,
        can find the pre-rescale cut (see the restore floor below)."""
        raw = str(self.config.get(ClusterOptions.RESCALE_FROM) or "")
        return [p.strip() for p in raw.split(",") if p.strip()]

    @staticmethod
    def _savepoint_seq(path: str) -> int:
        """Checkpoint-sequence number a savepoint directory was written
        under (paths end in savepoint-<n>; ids are fleet-aligned)."""
        import re

        m = re.findall(r"savepoint-(\d+)", str(path).replace("\\", "/"))
        return int(m[-1]) if m else -1

    def _load_repartitioned(self, primary: str) -> Dict[str, Any]:
        """Load an explicit restore path; when its key-group identity
        (writer nproc/pid) differs from this process's, load the FULL
        savepoint set named by cluster.rescale-from and merge it down to
        this process's shard range (checkpoint/repartition.py)."""
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        payload = FsCheckpointStorage.load(primary)
        me = self._rescale_identity()
        ident = payload.get("rescale")
        if ident is None or (
                int(ident.get("nproc", 1)) == me["nproc"]
                and int(ident.get("pid", 0)) == me["pid"]):
            # same shape (or a pre-identity snapshot): plain restore
            return payload
        from flink_tpu.checkpoint.repartition import merge_payloads

        paths = self._rescale_from_paths() or [primary]
        payloads = [payload if p == primary else FsCheckpointStorage.load(p)
                    for p in paths]
        payloads.sort(
            key=lambda pl: int((pl.get("rescale") or {}).get("pid", 0)))
        op_kinds = {nid: n.kind for nid, n in self.plan.nodes.items()
                    if nid in payload.get("operators", {})}
        return merge_payloads(
            payloads, new_pid=me["pid"], new_nproc=me["nproc"],
            num_shards=me["num_shards"],
            slots_per_shard=int(self.config.get(
                StateOptions.SLOTS_PER_SHARD)),
            op_kinds=op_kinds)

    def checkpoint_now(self, savepoint: bool = False):
        """Trigger one SYNCHRONOUS checkpoint at the current step
        boundary (ref: CheckpointCoordinator.triggerCheckpoint;
        savepoint=True for the manually-triggered retained form). The
        interval path in the run loop uses the async form instead —
        this entry point waits for durability before returning."""
        assert self._coordinator is not None, "checkpointing not configured"
        # the checkpoint already in flight is a PERIODIC one — its
        # failure is tolerable; the one triggered here is not
        self._complete_pending_checkpoint(wait=True, tolerate=True)
        self._ckpt_pending = self._begin_checkpoint(savepoint=savepoint)
        return self._complete_pending_checkpoint(wait=True)

    def _begin_checkpoint(self, savepoint: bool = False):
        """In-loop freeze + background persistence kickoff. The only
        loop-thread work is the emit flush, sink staging, and the
        snapshot freeze (device leaves are dispatched on-device clones);
        fetching/serializing/writing runs on the checkpoint executor."""
        # barrier part 1: in-flight async-I/O batches are NOT in the
        # snapshot (their source positions already advanced) — drain
        # them downstream first so the checkpoint covers their effects
        with self._push_lock:
            for nid, op in self._ops.items():
                if self.plan.node(nid).kind == "async_io":
                    for b in op.poll(drain=True):
                        self._push_downstream(nid, b)
        self._flush_emits()  # barrier: staged epoch must be complete
        sinks = [n.sink for n in self.plan.nodes.values() if n.kind == "sink"]
        commit_fns = [s.notify_checkpoint_complete for s in sinks]
        commit_fns.extend(self._source_offset_committers())
        pend = self._coordinator.trigger_async(
            lambda: self._snapshot(allow_reuse=not savepoint),
            commit_fns=commit_fns,
            prepare_fns=[s.prepare_commit for s in sinks],
            # abandon() (attempt failure with this checkpoint in
            # flight) notifies 2PC sinks to roll THIS epoch's staged
            # transaction back — recovery rolls uncommitted log
            # segments/parts back durably, not just in memory
            abort_fns=[s.notify_checkpoint_abort for s in sinks],
            executor=self._ckpt_executor,
            savepoint=savepoint,
        )
        pend.frozen_versions = dict(self._last_freeze_versions)
        pend.is_savepoint = savepoint
        return pend

    # -- cross-host data plane (SURVEY §3.6: the DCN exchange) -----------

    def _dcn_connect(self):
        """Build + connect this process's exchange endpoint and validate
        the v1 topology constraints (one source, one keyed window
        stage, shards divisible by the process count)."""
        from flink_tpu.exchange.dcn import DcnExchange

        cfg = self.config
        n = int(cfg.get(ClusterOptions.NUM_PROCESSES))
        pid = int(cfg.get(ClusterOptions.PROCESS_ID))
        peers = [p.strip() for p in
                 str(cfg.get(ClusterOptions.DCN_PEERS)).split(",")
                 if p.strip()]
        rendezvous = (not peers and str(cfg.get(
            ClusterOptions.DCN_RENDEZVOUS)).strip() == "coordinator")
        if not rendezvous and len(peers) != n:
            raise ValueError(
                f"cluster.dcn-peers must list {n} host:port entries, "
                f"got {len(peers)}")
        if len(self.plan.sources) != 1:
            raise NotImplementedError(
                "cross-process jobs support exactly one source in v1")
        keyed = [nd for nd in self.plan.nodes.values()
                 if nd.kind == "window"]
        if len(keyed) != 1:
            raise NotImplementedError(
                "cross-process jobs support exactly one keyed window "
                "stage in v1")
        num_shards = int(cfg.get(StateOptions.NUM_KEY_SHARDS))
        if num_shards % n:
            raise ValueError(
                f"state.num-key-shards ({num_shards}) must divide by "
                f"cluster.num-processes ({n}) — shards are the rescale "
                "unit (the key-group contract)")
        lat = keyed[0].window_transform.allowed_lateness_ms
        if lat:
            raise NotImplementedError(
                "allowed lateness across processes needs a refire "
                "consensus the v1 exchange does not carry")
        bind = str(cfg.get(ClusterOptions.DCN_BIND)).strip()
        if bind == "auto":
            # widen past loopback only when the configured topology is
            # actually cross-machine (see ClusterOptions.DCN_BIND)
            local = ("", "127.0.0.1", "localhost")
            hosts = [p.rpartition(":")[0].strip() for p in str(
                cfg.get(ClusterOptions.DCN_PEERS)).split(",") if p.strip()]
            hosts.append(str(cfg.get_raw("cluster.dcn-host", "")).strip())
            bind = ("0.0.0.0" if any(h and h not in local for h in hosts)
                    else "127.0.0.1")
        ex = DcnExchange(pid, n,
                         listen_port=int(cfg.get(ClusterOptions.DCN_PORT)),
                         bind_host=bind,
                         attempt=int(cfg.get_raw("cluster.attempt", 1)),
                         secret=str(cfg.get(
                             ClusterOptions.DCN_SECRET) or "") or None,
                         io_threads=int(cfg.get(
                             ClusterOptions.DCN_IO_THREADS)),
                         buffer_bytes=int(cfg.get(
                             ClusterOptions.DCN_BUFFER_BYTES)))
        try:
            if rendezvous:
                # coordinator-deployed job: publish this process's
                # listener and poll until the whole fleet registered
                # (ref: the reference's TaskManagers learning partition
                # locations from the JobMaster's deployment descriptors)
                from flink_tpu.runtime.rpc import RpcClient

                addr = str(cfg.get_raw("cluster.coordinator", "")).strip()
                job_id = str(cfg.get_raw("cluster.job-id", "job")).strip()
                attempt = int(cfg.get_raw("cluster.attempt", 1))
                dcn_host = str(cfg.get_raw("cluster.dcn-host",
                                           "127.0.0.1")).strip()
                host, _, port = addr.partition(":")
                c = RpcClient(host, int(port), timeout_s=5.0)
                try:
                    c.call("dcn_register", job_id=job_id, attempt=attempt,
                           process_id=pid, host=dcn_host, port=ex.port)
                    deadline = time.time() + 60.0
                    while True:
                        resp = c.call("dcn_peers", job_id=job_id,
                                      attempt=attempt, n_processes=n)
                        if resp.get("ready"):
                            peers = resp["peers"]
                            break
                        if time.time() > deadline:
                            raise TimeoutError(
                                "DCN rendezvous incomplete after 60s")
                        time.sleep(0.1)
                finally:
                    c.close()
            ex.connect(peers)
        except BaseException:
            # a half-connected endpoint must not outlive the attempt: a
            # LEAKED listener (live accept thread on a fixed
            # cluster.dcn-port) turns every recovery retry into
            # EADDRINUSE — the attempt could never rebind its own port
            ex.close()
            raise
        self._dcn_key_field = keyed[0].key_field
        self._dcn_shards = num_shards
        return ex

    def _dcn_negotiated_restore(self):
        """Agree on ONE checkpoint id across processes (the min of
        everyone's latest) and load it; None when any process has no
        checkpoint — everyone then replays from scratch together."""
        latest = self._coordinator.storage.latest()
        my_id = latest.checkpoint_id if latest is not None else -1
        _, metas = self._dcn.exchange({}, {"latest": int(my_id)})
        common = min(int(m["latest"]) for m in metas)
        if common < 0:
            return None
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        # last match: list_complete sorts by (id, epoch), so among
        # fence-epoch duplicates of the negotiated id the successor's
        # (highest-epoch) directory wins
        match = [h for h in self._coordinator.storage.list_complete()
                 if h.checkpoint_id == common and not h.is_savepoint]
        if match:
            payload = FsCheckpointStorage.load(match[-1])
            self._coordinator.resume_numbering(payload)
            return payload
        raise RuntimeError(
            f"negotiated checkpoint id {common} is missing locally — "
            "retention removed it; raise state.checkpoints.num-retained")

    def _ingest_loop_dcn(self, srcs, interval_ms: int,
                         job_name: str = "job") -> None:
        """The cross-host step loop: ingest a local batch, route records
        to their shard owners, RENDEZVOUS (the step barrier carrying
        watermark / termination / checkpoint consensus), then run the
        local pipeline on this process's share. See exchange/dcn.py for
        why the rendezvous replaces flow control, in-band watermarks,
        and barrier alignment.

        STEP OVERLAP (``cluster.dcn-overlap``, default on): step k+1's
        frames are dispatched BEFORE step k's are consumed, so one
        step's exchange is always in flight while the device computes
        the previous step's records and the host ingests/routes the
        next — the rendezvous barrier moves from dispatch to
        consumption. The per-step consensus is untouched (metas are
        identical fleet-wide, so every process makes the same
        checkpoint/termination decision one step later), and a
        checkpoint barrier DRAINS the one in-flight step first
        (``cluster.dcn-overlap-drain``) so the cut still covers every
        routed record — disabling the drain is the analyzer-flagged
        at-most-once trade (DCN_OVERLAP_UNSAFE).

        ``pipeline.sub-batches`` = K > 1: this process's merged share is
        pushed as K contiguous slices with fire dispatches between them
        (``_push_dcn_merged``) — dispatch granularity shrinks K-fold
        while the GLOBAL watermark still advances once per rendezvous
        (the clock is fleet consensus; a sub-step advance would need a
        sub-step rendezvous), so committed rows stay byte-identical to
        K=1."""
        from flink_tpu.exchange.partitioners import hybrid_route

        cfg = self.config
        n = int(cfg.get(ClusterOptions.NUM_PROCESSES))
        pid = int(cfg.get(ClusterOptions.PROCESS_ID))
        key_field = self._dcn_key_field
        (sid,) = list(self.plan.sources)
        d = srcs[sid]
        order = sorted(d)
        ex = self._dcn
        overlap = (bool(cfg.get(ClusterOptions.DCN_OVERLAP))
                   and ex.supports_async)
        drain_at_barrier = bool(cfg.get(ClusterOptions.DCN_OVERLAP_DRAIN))
        st = _DcnStepState(last_chk=time.time())
        pending_x = None        # the ONE in-flight overlapped step
        stale_ckpt = False      # drain-off mode: the undrained step's
        # meta was dispatched BEFORE the snapshot it rode behind, so
        # its ckpt flag is stale — absorb it once (symmetric: every
        # process just checkpointed at the same boundary), or the
        # fleet double-checkpoints back-to-back every interval
        stale_sp = False        # same staleness for the savepoint flag:
        # the in-flight step's meta predates the savepoint just served
        while True:
            if self._cancel is not None and self._cancel.is_set():
                # stop-with-savepoint (rescale) sets cancel from the
                # savepoint completion callback; exit symmetrically at
                # the next boundary — every process served the request
                # at the SAME rendezvous, so the fleet leaves together
                raise JobCancelledError(job_name)
            batch = None
            batch_ix = None
            while order:
                ix = order[0]
                nxt = next(d[ix], None)
                if nxt is None:
                    order.pop(0)
                    continue
                batch = nxt
                batch_ix = ix
                self._advance_position(sid, ix, nxt[0], nxt[1])
                break
            shares: Dict[int, Any] = {}
            if batch is not None:
                data, ts = batch
                ts = np.asarray(ts, np.int64)
                if len(ts):
                    mx = int(ts.max())
                    self._max_ts[sid] = max(self._max_ts[sid], mx)
                    self._wm_gens[sid][batch_ix].on_batch(mx)
                keys = np.asarray(data[key_field], np.int64)
                # process destination from the ONE routing truth the
                # hybrid mesh plan also uses (exchange/partitioners.py):
                # intra-slice records (dest == pid) never touch the
                # wire — they ride shares[pid] straight into the local
                # push, and the in-process device mesh distributes them
                # over ICI
                dest, _ = hybrid_route(keys, self._dcn_shards, n)
                for j in range(n):
                    m = dest == j
                    if m.any():
                        shares[j] = {
                            "data": {k: np.asarray(v)[m]
                                     for k, v in data.items()},
                            "ts": ts[m]}
            local_wm = (min(self._wm_gens[sid][i].current() for i in order)
                        if order else _FINAL)
            want_ckpt = (pid == 0 and self._coordinator is not None
                         and interval_ms > 0
                         and (time.time() - st.last_chk) * 1000
                         >= interval_ms)
            sp_rq = self._savepoint_request
            meta = {"wm": int(local_wm), "done": batch is None,
                    "ckpt": bool(want_ckpt),
                    # savepoint consensus: the coordinator triggers the
                    # request on EVERY process (require-all push); the
                    # flag rides the rendezvous so the fleet serves it
                    # at ONE common step boundary — the savepoint set
                    # is a globally consistent cut, like "ckpt" but
                    # all-set instead of any-set (no clock owner)
                    "sp": bool(sp_rq is not None and sp_rq.is_set()),
                    # 2PC phase-2 ack: the id this process has DURABLY
                    # persisted (commit waits until everyone has it —
                    # the reference's all-acks-then-notifyComplete rule,
                    # 4.C, carried on the rendezvous instead of RPC)
                    "persisted": int(st.persisted_id)}
            h = ex.exchange_async(shares, meta)
            if overlap and pending_x is None:
                # prime the double buffer: nothing to consume yet
                pending_x = h
                continue
            target, pending_x = (pending_x, h) if overlap else (h, None)
            all_done, ckpt_req, sp_req = self._dcn_consume_step(
                sid, target, st, deferred=overlap)
            if stale_ckpt:
                ckpt_req = False
                stale_ckpt = False
            if stale_sp:
                sp_req = False
                stale_sp = False
            if not (all_done or ckpt_req or sp_req):
                continue
            if pending_x is not None and (all_done or drain_at_barrier):
                # drain the in-flight step so the snapshot cut (or the
                # final barrier) covers its routed records. Its own
                # consensus flags are ABSORBED — metas are identical
                # fleet-wide, so every process absorbs the same ones —
                # except termination, which must still be honored.
                done2, _, _ = self._dcn_consume_step(sid, pending_x, st,
                                                     absorb=True,
                                                     deferred=True)
                all_done = all_done or done2
                pending_x = None
            if ckpt_req:
                # checkpoint consensus: process 0's clock decided, the
                # flag rode the rendezvous, so EVERY process snapshots
                # at this same step boundary — a globally consistent
                # cut (SURVEY §6.4's step-barrier insight). With the
                # drain above there are no in-flight records; with
                # cluster.dcn-overlap-drain=false the one in-flight
                # step's records are NOT covered (the analyzer-warned
                # at-most-once trade).
                if self._coordinator is not None and st.pending is None:
                    st.pending = self._begin_checkpoint()
                    self._ckpt_pending = st.pending
                    st.pending.future.result()  # durable before acking
                    st.pending_id = st.pending.checkpoint_id
                    st.persisted_id = st.pending_id
                st.last_chk = time.time()
                # without the drain, the in-flight step still carries
                # its pre-snapshot ckpt flag — consume it ABSORBED
                stale_ckpt = pending_x is not None
            if sp_req:
                # every process has the pending request (all-set above):
                # serve it HERE, at the common boundary, each with its
                # own token/stop identity. The savepoint commits
                # synchronously fleet-wide — symmetric, so no ack dance.
                self._maybe_take_savepoint()
                if (st.pending is not None
                        and self._ckpt_pending is not st.pending):
                    # the savepoint path completed the in-flight
                    # periodic checkpoint (checkpoint_now waits on it);
                    # forgetting that here would double-complete it at
                    # the next persisted-ack consensus
                    st.pending = None
                stale_sp = pending_x is not None
            if all_done:
                if st.pending is not None:
                    # end of input doubles as the final barrier: every
                    # process reached it, so the last cut is global
                    st.pending.complete()
                    self._ckpt_pending = None
                return

    def _dcn_consume_step(self, sid: int, handle, st: "_DcnStepState",
                          absorb: bool = False,
                          deferred: bool = False):
        """Consume ONE rendezvous step: barrier on the handle, push the
        merged share through the local pipeline, apply the global
        watermark, and run the 2PC persisted-ack check. Returns
        (all_done, ckpt_requested, savepoint_requested); ``absorb``
        suppresses the ckpt and savepoint flags
        (the drained step rides the barrier that drained it);
        ``deferred`` marks an OVERLAPPED consume — the only place the
        dcn.overlap.consume fault point fires, so a chaos bisect of
        the overlap seam stays quiet on lockstep runs."""
        if deferred:
            from flink_tpu import faults

            faults.fire("dcn.overlap.consume", exc=ConnectionError)
        payloads, metas = handle.result()
        parts = [p for p in payloads if p is not None
                 and len(p["ts"])]
        if parts:
            md = {k: np.concatenate([p["data"][k] for p in parts])
                  for k in parts[0]["data"]}
            mts = np.concatenate([p["ts"] for p in parts])
            self._push_dcn_merged(sid, md, mts)
            for op in self._ops.values():
                if hasattr(op, "throttle"):
                    op.throttle()
            self._eps_meter.mark(len(mts))
        # identical global watermark on every process: min of the
        # piggybacked locals (exhausted processes report _FINAL so
        # they stop pinning the clock)
        gwm = min(int(m["wm"]) for m in metas)
        if gwm != _FINAL and gwm > self._out_wm[sid]:
            self._out_wm[sid] = gwm
        with self._push_lock:
            self._propagate_watermarks()
        self._check_drain_error()
        # commit the PREVIOUS checkpoint once every process acked
        # durability (phase 2): only then may 2PC sinks publish
        if (st.pending is not None
                and all(int(m.get("persisted", -1)) >= st.pending_id
                        for m in metas)):
            st.pending.complete()
            self._ckpt_pending = None
            st.pending = None
        ckpt_req = (not absorb) and any(bool(m.get("ckpt")) for m in metas)
        # all-set (vs ckpt's any-set): a savepoint is triggered per
        # process over RPC, so the LAST process to receive it gates the
        # barrier — serving before everyone holds the request would cut
        # at different steps and the set would not be a consistent cut
        sp_req = (not absorb) and all(bool(m.get("sp")) for m in metas)
        return all(bool(m["done"]) for m in metas), ckpt_req, sp_req

    def _push_dcn_merged(self, sid: int, md, mts) -> None:
        """Push this process's merged exchange share downstream — as
        ONE batch at K=1 (the exact pre-sub-batch path), or as K
        contiguous slices with a fire-dispatch pass between them at
        ``pipeline.sub-batches`` = K > 1, so device dispatch granularity
        and fire/drain cadence shrink K-fold cross-host too. Record
        order is untouched (slices are contiguous) and the global
        watermark is applied by the CALLER after the whole push, so
        late classification — and committed rows — are byte-identical
        across K."""
        nrec = len(mts)
        valid = np.ones(nrec, bool)
        k = self._sub_batches
        if k <= 1 or nrec <= k:
            with self._push_lock:
                self.metrics["records_in"] += nrec
                self.metrics["batches"] += 1
                self._push_downstream(sid, (md, mts, valid))
            return
        with self._push_lock:
            self.metrics["records_in"] += nrec
            self.metrics["batches"] += 1
        sub = -(-nrec // k)  # ceil: ragged tails allowed cross-host
        for lo in range(0, nrec, sub):
            hi = min(lo + sub, nrec)
            with self._push_lock:
                self._push_downstream(
                    sid, ({kk: v[lo:hi] for kk, v in md.items()},
                          mts[lo:hi], valid[lo:hi]))
                self._propagate_watermarks()
            self._check_drain_error()

    def _maybe_chain_device_source(self, sid: int, n) -> None:
        """Chain a DeviceGeneratorSource into its consuming window
        operator when the topology allows it: single downstream window
        node keyed on the source's key field, single process, and an
        operator config the devgen kernel can host (the operator's own
        gate). Any miss falls back to normal host materialization.

        ``pipeline.sub-batches`` > 1: the source is SUBDIVIDED before
        attach — the operator's step program runs at sub-batch
        granularity (bit-exact slices of the logical stream), so fires
        ride each sub-step's dispatch and positions count sub-batches
        (``self._sub_factor[sid]``). A source that declares no
        subdivision chains at logical granularity — sub-batch fire
        cadence then applies only to host-fed sources."""
        from flink_tpu.api.sources import DeviceGeneratorSource

        src = n.source
        if (not isinstance(src, DeviceGeneratorSource)
                or src.device_keys_ts is None or self._dcn is not None
                or len(n.downstream) != 1):
            return
        wid = n.downstream[0]
        wn = self.plan.node(wid)
        if (wn.kind != "window"
                or getattr(wn, "key_field", None) != src.key_field):
            return
        factor = 1
        if self._sub_batches > 1 and src.subdivide is not None:
            # a declared-but-failing subdivision is a config error (the
            # source's batch size does not split into K) — loud, not a
            # silent fall back to full-batch fire cadence
            src = src.subdivided(self._sub_batches)
            factor = self._sub_batches
        op = self._ops.get(wid)
        if op is not None and hasattr(op, "attach_device_source") \
                and op.attach_device_source(src):
            self._dev_chains[sid] = wid
            if factor > 1:
                self._sub_factor[sid] = factor
                self._dev_subdivided[sid] = src
            elif self._sub_batches > 1:
                # the chain stays at LOGICAL granularity (no subdivide
                # callable): the ×K in-flight credit from _build_ops
                # would let K× the bytes queue before throttle engages
                # — restore the base depth for this operator
                op.max_inflight_steps = self._base_inflight

    def _enumerate_owned(self, sid: int, n_splits: int) -> List[int]:
        """Which split indices THIS runner reads (ref: FLIP-27
        SplitEnumerator on the JM assigning splits to readers — SURVEY
        §3.3 source runtime). 'local' (default) = all splits (single-
        process execution); 'coordinator' = ask the job coordinator for
        this runner's share, so multiple runners of one job divide the
        source without overlap."""
        from flink_tpu.config import SourceOptions

        mode = self.config.get(SourceOptions.ENUMERATION)
        nproc = int(self.config.get(ClusterOptions.NUM_PROCESSES))
        if mode == "local" and nproc > 1:
            # cross-host job without a coordinator-side enumerator:
            # deterministic strided shares (the same disjointness rule
            # rpc_enumerate_splits uses)
            pid = int(self.config.get(ClusterOptions.PROCESS_ID))
            return list(range(pid, n_splits, nproc))
        if mode == "local" or n_splits == 0:
            return list(range(n_splits))
        if mode != "coordinator":
            raise ValueError(
                f"source.enumeration must be 'local' or 'coordinator', "
                f"got {mode!r}")
        from flink_tpu.runtime.rpc import RpcClient

        addr = str(self.config.get_raw("cluster.coordinator", "")).strip()
        job_id = str(self.config.get_raw("cluster.job-id", "")).strip()
        runner_id = str(self.config.get_raw("cluster.runner-id", "")).strip()
        if not (addr and job_id and runner_id):
            raise ValueError(
                "source.enumeration=coordinator needs cluster.coordinator"
                ", cluster.job-id and cluster.runner-id (the runner "
                "injects them on deploy)")
        host, _, port = addr.partition(":")
        c = RpcClient(host, int(port), timeout_s=10.0)
        try:
            resp = c.call("enumerate_splits", job_id=job_id,
                          source_id=sid, n_splits=n_splits,
                          runner_id=runner_id)
        finally:
            c.close()
        return [int(i) for i in resp["splits"]]

    def _debloat_split(self, data, ts):
        """Re-chunk one source batch to the debloater's current chunk
        size (no-op generator when the debloater is off or the batch
        already fits). Slicing preserves record order, so watermark
        semantics are untouched — the generators see the same max ts."""
        n = len(ts)
        chunk = self._debloat_chunk
        if self._debloat_target <= 0 or chunk is None or n <= chunk:
            if self._debloat_target > 0 and self._debloat_chunk is None and n:
                self._debloat_chunk = n  # seed at the source batch size
                # (empty first batches — unbounded sources idling — must
                # not seed a zero chunk)
            yield data, ts
            return
        chunk = max(1, chunk)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            yield ({k: v[lo:hi] for k, v in data.items()}, ts[lo:hi])

    def _debloat_adjust(self) -> None:
        """One control step (ref: BufferDebloater.recalculateBufferSize):
        recent emit p99 > target → halve the chunk; p99 < target/2 →
        grow 2x (cap: whatever the source produces — _debloat_split
        never merges). Needs a few fresh samples to act."""
        if self._debloat_target <= 0 or self._debloat_chunk is None:
            return
        # act only on FRESH samples: the ingest loop passes far more
        # often than windows fire, and re-halving on the same stale
        # window would pin the chunk at the floor after one slow burst
        c = self._lat_hist.count
        if c - self._debloat_seen < 2:
            return
        self._debloat_seen = c
        p99 = self._lat_hist.quantile_recent(0.99, window=16)
        if p99 > self._debloat_target:
            self._debloat_chunk = max(self._debloat_min,
                                      self._debloat_chunk // 2)
        elif p99 < self._debloat_target / 2:
            self._debloat_chunk *= 2

    def _maybe_take_savepoint(self) -> None:
        """Operator-triggered savepoint (CLI `savepoint`): synchronous +
        retained, at a batch boundary; the completed path is pushed to
        the requester's on_complete hook (runner → coordinator → CLI
        status). A request with no checkpoint storage is rejected at the
        runner, so _coordinator is always set when the flag can be."""
        req = self._savepoint_request
        if req is None or not req.is_set():
            return
        # snapshot the request's identity BEFORE clearing: the moment
        # the event clears, a new trigger may overwrite stop_after/token
        # on the shared request object while the (long, synchronous)
        # savepoint write runs — completion must report the values of
        # the request it actually served
        stop_after = getattr(req, "stop_after", False)
        token = getattr(req, "token", None)
        req.clear()
        if self._coordinator is None:
            return  # unreachable via the runner path (validated there)
        h = self.checkpoint_now(savepoint=True)
        self.last_savepoint = h.path
        cb = getattr(req, "on_complete", None)
        if cb is not None:
            # arity by signature, NOT by catching TypeError — a TypeError
            # raised INSIDE the callback must not trigger a second,
            # wrongly-argumented invocation (double savepoint report)
            import inspect

            try:
                params = inspect.signature(cb).parameters
                rich = ("stop_after" in params
                        or any(p.kind == p.VAR_KEYWORD
                               for p in params.values()))
            except (TypeError, ValueError):
                rich = False
            if rich:
                cb(h.path, stop_after=stop_after, token=token)
            else:
                cb(h.path)  # simple callbacks (tests) take path only

    def _complete_pending_checkpoint(self, wait: bool = False,
                                     tolerate: bool = False):
        """Apply the 2PC commit of a finished background checkpoint on
        the LOOP thread (the asynchronous notifyCheckpointComplete of
        the reference). Non-blocking unless ``wait``.

        ``tolerate``: the PERIODIC path rides out up to
        execution.checkpointing.tolerable-failures consecutive
        persist/commit failures instead of failing the job — the failed
        id left no manifest at its final name, so restore ignores it,
        and the staged 2PC epoch simply commits with the next
        successful checkpoint. Savepoints and the final end-of-input
        checkpoint never tolerate (their durability IS the contract)."""
        import os as _os

        p = self._ckpt_pending
        if p is None:
            return None
        if not wait and not p.done():
            return None
        try:
            handle = p.complete()
        except Exception as e:  # noqa: BLE001 — persist/commit failure
            self._ckpt_pending = None
            if p.is_savepoint:
                # savepoints neither count toward nor reset the
                # CONSECUTIVE-PERIODIC-failure budget (the option's
                # documented unit)
                raise
            self._ckpt_failures += 1
            tol = int(self.config.get(
                CheckpointingOptions.TOLERABLE_FAILURES))
            if not tolerate or self._ckpt_failures > tol:
                raise
            from flink_tpu.obs.tracing import tracer

            self.metrics["checkpoint_failures"] = (
                self.metrics.get("checkpoint_failures", 0) + 1)
            with tracer.span("checkpoint.failed",
                             checkpoint_id=p.checkpoint_id,
                             consecutive=self._ckpt_failures,
                             error=f"{type(e).__name__}: {e}"):
                pass
            return None
        if not p.is_savepoint:
            # a savepoint landing between two periodic failures must
            # not reset the consecutive-periodic counter either
            self._ckpt_failures = 0
        self._ckpt_pending = None
        if not p.is_savepoint:
            names = handle.op_files or {}
            aux_names = handle.op_aux or {}
            self._ckpt_base = {
                "files": {nid: _os.path.join(
                    handle.path, names.get(str(nid), f"op-{nid}.blob"))
                    for nid in self._ops},
                "versions": dict(p.frozen_versions),
                "aux": {nid: {logical: _os.path.join(handle.path, fn)
                              for logical, fn in
                              aux_names.get(str(nid), {}).items()}
                        for nid in self._ops
                        if aux_names.get(str(nid))},
            }
        return handle

    # -- run loop --------------------------------------------------------
    def run(self, job_name: str = "job", cancel=None,
            savepoint_request=None):
        """``cancel``: optional threading.Event checked at every batch
        boundary; when set the run aborts with JobCancelledError through
        the normal failure cleanup (no output reaches sinks).
        ``savepoint_request``: optional threading.Event; when set, the
        loop takes a SAVEPOINT at the next batch boundary (the CLI's
        `savepoint` command rides this), clears the event, and records
        the path in ``self.last_savepoint``."""
        self._cancel = cancel
        self._savepoint_request = savepoint_request
        self.last_savepoint = None
        if self.plan.runtime_mode == "batch":
            # bounded-mode recovery is re-execution (ref: batch jobs
            # have no checkpoints — RestartAllFailoverStrategy re-runs
            # the regions); a configured interval/restore is a config
            # contradiction, not something to silently ignore
            if self.config.get(CheckpointingOptions.INTERVAL) > 0:
                raise ValueError(
                    "execution.checkpointing.interval is incompatible "
                    "with execution.runtime-mode=batch (bounded-mode "
                    "recovery is re-execution; 2PC sinks commit once "
                    "at end of input)")
            restore = self.config.get(CheckpointingOptions.RESTORE)
            if restore == "latest":
                # coordinator/supervisor redeploys inject
                # restore=latest on every retry attempt; for a batch
                # job there is never a checkpoint to resume, and its
                # documented recovery model IS re-execution — degrade
                # to a fresh run instead of burning the restart budget
                # on a config error that masks the original failure
                self.config.set(CheckpointingOptions.RESTORE, "")
            elif restore:
                raise ValueError(
                    "execution.checkpointing.restore is incompatible "
                    "with execution.runtime-mode=batch (nothing "
                    "checkpoints in batch mode — re-run the job)")
        # compile-time plan analysis at submit (flink_tpu/analysis/):
        # findings surface BEFORE the first record flows; the fail-on
        # threshold decides which severities abort the run, everything
        # else stays inspectable on driver.analysis_findings. Runs
        # after the explicit batch-mode contradictions above so their
        # long-standing error messages keep first claim.
        from flink_tpu.config import AnalysisOptions

        fail_on = str(self.config.get(AnalysisOptions.FAIL_ON)).strip().lower()
        self.analysis_findings = []
        if fail_on != "off":
            from flink_tpu.analysis import AnalysisError, analyze
            from flink_tpu.analysis.core import blocking

            # eval_chains=False: the automatic submit pass must never
            # CALL user chain fns (a side-effecting map would observe a
            # phantom empty batch); schema facts go opaque at the first
            # unevaluated chain. `env.analyze()` / the CLI evaluate.
            self.analysis_findings = analyze(self.plan, self.config,
                                             eval_chains=False)
            blockers = blocking(self.analysis_findings, fail_on)
            if blockers:
                raise AnalysisError(blockers, fail_on)
        import queue
        import threading

        from flink_tpu.obs.metrics import METRICS_BIND, METRICS_PORT, MetricsServer

        self._coordinator = self._setup_checkpointing(job_name)
        # announce this attempt's fencing epoch to transactional sinks
        # BEFORE any restore/write: epoch-qualified in-progress names
        # (part files, log segments) keep a deposed attempt's late
        # renames off a successor's committed output — the same
        # chk-<id>.e<epoch> discipline checkpoint storage uses
        attempt_epoch = int(self.config.get_raw("cluster.attempt", 0))
        for n in self.plan.nodes.values():
            if n.kind == "sink":
                setter = getattr(n.sink, "set_attempt_epoch", None)
                if setter is not None:
                    setter(attempt_epoch)
                # the shared HostPool rides the same announcement seam:
                # transactional log sinks route per-partition segment
                # writes + the group-fsync pass through it so a
                # multi-partition stage() scales with cores
                pool_setter = getattr(n.sink, "set_host_pool", None)
                if pool_setter is not None:
                    pool_setter(self.host_pool)
        from concurrent.futures import ThreadPoolExecutor

        from flink_tpu import faults
        from flink_tpu.fs import install_enospc_policy_from_config

        # the disk-full degradation policy (storage.enospc-policy):
        # installed process-wide at run start so every durable write
        # seam — checkpoint persists, log segment stages, sink part
        # writes — follows the job's declared retry/fail behavior
        install_enospc_policy_from_config(self.config)
        # fault-scope propagation (session tenant isolation): the run
        # executes on a thread the runner already scoped to this job;
        # the threads the DRIVER owns — drain, checkpoint executor —
        # must carry the same scope or a tenant's checkpoint/upload
        # fault rules would miss its own background work
        self._fault_scope = faults.current_scope()
        self._ckpt_executor = (ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt",
            initializer=faults.set_thread_scope,
            initargs=(self._fault_scope,))
            if self._coordinator is not None else None)
        self._ckpt_pending = None
        self._ckpt_base = None
        self._ckpt_failures = 0  # consecutive, for tolerable-failures
        self._last_freeze_versions: Dict[Any, int] = {}
        interval_ms = self.config.get(CheckpointingOptions.INTERVAL)
        restore = self.config.get(CheckpointingOptions.RESTORE)
        self._positions: Dict[int, Dict[int, int]] = {}
        port = self.config.get(METRICS_PORT)
        bind = self.config.get(METRICS_BIND)
        self._metrics_server = (
            MetricsServer(self.registry, port, bind) if port else None)
        self._emit_q = queue.Queue()
        self._drain_discard = [False]  # fresh cell per run (see __init__)
        # per-op device profiling window (pipeline.profile-dir): wraps
        # N warm driver steps in jax.profiler.trace and reduces the
        # trace to a per-op summary (obs/profiling.py) — the §8.5 seam
        from flink_tpu.obs.profiling import StepProfiler

        self._profiler = StepProfiler.from_config(self.config)
        # self-maintaining bus tier (log.cleaner.enabled): one leased
        # background cleaner service per LogSink topic, running
        # compaction + retention at log.cleaner.interval-ms under the
        # cleaner lease + the per-topic maintenance lock — racing this
        # run's own producer/consumers by design (the manifest-swap
        # discipline keeps reads byte-identical). A second driver on
        # the same topic fails the acquire and runs WITHOUT a cleaner
        # (the lease's point: exactly one cleaner per topic).
        self._cleaners = []
        from flink_tpu.config import LogOptions

        if bool(self.config.get(LogOptions.CLEANER_ENABLED)):
            from flink_tpu.log.cleaner import LogCleaner
            from flink_tpu.log.connectors import LogSink
            from flink_tpu.log.topic import LogError

            seen = set()
            for n in self.plan.nodes.values():
                if n.kind != "sink" or not isinstance(n.sink, LogSink):
                    continue
                if n.sink.path in seen:
                    continue
                seen.add(n.sink.path)
                cleaner = LogCleaner(n.sink.path, self.config)
                try:
                    cleaner.start()
                except LogError:
                    continue  # a live cleaner service owns this topic
                self._cleaners.append(cleaner)
        drain = threading.Thread(target=self._drain_entry, daemon=True)
        drain.start()
        try:
            return self._run_loop(job_name, drain, interval_ms, restore)
        except BaseException:
            # Failed attempt: an in-flight background checkpoint must
            # NOT commit its 2PC epoch (its snapshot may cover state the
            # failure invalidated); abandon it uncommitted — the
            # manifest may still land, which is harmless: restore picks
            # it up with its staged (uncommitted) epochs exactly like a
            # crash between manifest and commit.
            if getattr(self, "_ckpt_pending", None) is not None:
                self._ckpt_pending.abandon()
                # bounded wait for a persist already running: the next
                # attempt may reuse this checkpoint id, and two live
                # writers on one id is the corruption the unique tmp
                # dirs defend against — prefer not to race at all (a
                # wedged network fs must still not turn a crash into a
                # hang, hence the timeout)
                from concurrent.futures import wait as _fwait

                _fwait([self._ckpt_pending.future], timeout=30.0)
                self._ckpt_pending = None
            # Stop the drain thread BEFORE the exception
            # escapes, discarding everything it still holds. A daemon
            # drain left running would deliver this attempt's fires into
            # sinks reused by the next attempt — duplicate output after
            # recovery (exactly-once ref: StreamTask.cleanUpInternal
            # cancels the mailbox + output flusher before failover).
            self._drain_discard[0] = True
            self._flush_req.set()
            if self._emit_q is not None:
                self._emit_q.put(None)
                # bounded: the drain may be wedged inside the very device
                # fetch that killed the run — never convert a crash into
                # a hang. An abandoned drain is a daemon and keeps its
                # (permanently-set) discard cell: a late wakeup delivers
                # nothing, ever.
                drain.join(timeout=10.0)
                self._emit_q = None
            self._drain_error = None
            self._flush_req.clear()
            # a DCN endpoint alive past its attempt (the negotiated
            # restore or source setup failed before the ingest loop's
            # own close) would hold its fixed cluster.dcn-port —
            # every recovery rebind then dies with EADDRINUSE
            if getattr(self, "_dcn", None) is not None:
                self._dcn.close()
                self._dcn = None
            # rows delivered BEFORE the crash still sit in sink buffers;
            # drop them here too — the restore path only runs when the
            # next attempt configures restore (ref: StreamTask
            # .cleanUpInternal aborts pending transactions in cleanup)
            self._abort_sinks()
            # unblock + join prefetch feeders: one blocked thread and
            # `depth` buffered batches would leak per split per attempt.
            # Duck-typed: covers _Prefetcher AND source iterators that
            # own background work of their own (LogSource's segment
            # readahead exposes close() on its split iterator)
            for its in getattr(self, "_srcs", {}).values():
                for it in its.values():
                    closer = getattr(it, "close", None)
                    if closer is not None:
                        closer()
            if self._metrics_server is not None:
                self._metrics_server.close()
            for nid, op in self._ops.items():
                if self.plan.node(nid).kind == "async_io":
                    op.close()
            # a trace window left open by the failure must be stopped —
            # a dangling jax profiler session would poison the next run
            if self._profiler is not None:
                self._profiler.close()
            raise
        finally:
            # cleaners die with the run, releasing their leases so a
            # successor (or a manual pass) acquires immediately — on
            # EVERY exit path (a crashed process skips this; ttl
            # expiry + epoch bump is that takeover path)
            for cleaner in getattr(self, "_cleaners", []):
                try:
                    cleaner.stop()
                except Exception:
                    pass  # teardown must not mask the run's outcome
            self._cleaners = []
            if self._ckpt_executor is not None:
                # non-blocking: an abandoned persist may still be
                # writing; letting it finish is safe (manifest-last)
                self._ckpt_executor.shutdown(wait=False)
                self._ckpt_executor = None
            # the shared host pool dies with the run (a wedged task
            # must not hang teardown: shutdown is non-waiting, and a
            # post-close straggler call degrades to the inline path)
            self.host_pool.close()

    def _run_loop(self, job_name: str, drain, interval_ms: int,
                  restore) -> "JobResult":
        from flink_tpu.api.environment import JobResult
        for sid in self.plan.sources:
            n = self.plan.node(sid)
            strategy = n.watermark_strategy or self.plan.watermark_strategy
            # one watermark generator PER SPLIT, combined with min — the
            # per-channel rule (ref: StatusWatermarkValve; a lagging split
            # must hold the source watermark back or its records would be
            # dropped as late)
            self._wm_gens[sid] = [make_generator(strategy)
                                  for _ in n.source.splits()]
            self._max_ts[sid] = LONG_MIN
            self._positions[sid] = {i: 0 for i in range(len(n.source.splits()))}

        # cross-host data plane: bring the DCN exchange up BEFORE
        # restore — the restore id is negotiated across processes (a
        # crash can leave one process a checkpoint ahead; replaying
        # from mismatched ids would double-count the laggard's records
        # in the leader's shard ranges)
        self._dcn = None
        if int(self.config.get(ClusterOptions.NUM_PROCESSES)) > 1:
            if self.plan.runtime_mode == "batch":
                raise NotImplementedError(
                    "execution.runtime-mode=batch is single-process in "
                    "v1 — the DCN rendezvous is a per-step streaming "
                    "protocol; cross-host batch needs a partition-file "
                    "transfer plane (out of scope, see COMPONENTS #57)")
            self._dcn = self._dcn_connect()

        # per-source sub-batch factor the restored checkpoint's positions
        # were written under (see _snapshot "sub_factors"); {} = fresh
        # run or pre-sub-batch checkpoint (factor 1 everywhere)
        self._restored_sub_factors: Dict[int, int] = {}
        if restore:
            if restore == "latest":
                payload = (self._dcn_negotiated_restore()
                           if self._dcn is not None
                           else self._coordinator.restore_latest())
                # durable rescale floor: cluster.rescale-from names the
                # savepoint set the last rescale redeploy restored from.
                # A checkpoint OLDER than that set predates the cut —
                # at 1->2->1 the final process count reuses the original
                # (unsuffixed) checkpoint directory, whose latest entry
                # is PRE-rescale state; resurrecting it would replay
                # records both savepoint cuts already cover, at a stale
                # key-group geometry. The savepoints win unless a
                # checkpoint at least as new exists.
                paths = self._rescale_from_paths()
                if paths:
                    floor = max(self._savepoint_seq(p) for p in paths)
                    have = (int(payload.get("checkpoint_id", -1))
                            if payload is not None else -1)
                    if have < floor:
                        payload = self._load_repartitioned(paths[0])
                        self._coordinator.resume_numbering(payload)
            else:
                payload = self._load_repartitioned(restore)
                self._coordinator.resume_numbering(payload)
            if payload is not None:
                self._restore(payload)
            else:
                # restore requested but nothing to restore (crash before
                # the first checkpoint): a sink instance reused across
                # attempts still holds the crashed attempt's staged rows —
                # the full replay would commit them twice
                self._abort_sinks()

        # registered on self INCREMENTALLY so prefetchers opened before a
        # mid-construction open_split failure are reachable from run()'s
        # failure cleanup. Keyed by GLOBAL split index: with
        # coordinator-side enumeration this runner opens only the
        # indices the enumerator assigned it, but positions/watermark
        # state stay globally indexed (checkpoints are runner-agnostic).
        srcs = self._srcs = {}
        self._owned_splits: Dict[int, List[int]] = {}
        # device-chained generator sources: source synthesized inside
        # the window operator's step program (see DeviceGeneratorSource
        # + ops/window.py devgen_step_kernel); maps sid -> window nid
        self._dev_chains: Dict[int, int] = {}
        # sid -> the SUBDIVIDED source actually iterated this run
        # (pipeline.sub-batches > 1 on a device chain); marker
        # iteration, gen fallback, and positions all use it
        self._dev_subdivided: Dict[int, Any] = {}
        prefetch = self.config.get(PipelineOptions.SOURCE_PREFETCH)
        for sid in self.plan.sources:
            n = self.plan.node(sid)
            if self.plan.runtime_mode != "batch":
                # batch mode keeps the host materialization path: the
                # devgen chain fuses per-step fire logic into the step
                # program, which final-only firing deliberately skips
                self._maybe_chain_device_source(sid, n)
            # restored positions were written in the restoring run's
            # sub-batch units — re-base them to THIS run's factor. Only
            # positions landing on a common sub-batch boundary convert
            # (a checkpoint cut mid-logical-batch at K=4 cannot resume
            # at K=3); misaligned factors fail loudly here rather than
            # silently replaying a partial logical batch.
            old_f = int(self._restored_sub_factors.get(sid, 1))
            new_f = int(self._sub_factor.get(sid, 1))
            if old_f != new_f:
                for i, p in list(self._positions[sid].items()):
                    self._positions[sid][i] = _rebase_position(
                        int(p), old_f, new_f, sid=sid, split_ix=i)
            splits = n.source.splits()
            owned = self._enumerate_owned(sid, len(splits))
            self._owned_splits[sid] = owned
            if not owned and self._dcn is None:
                # this runner owns nothing of the source: exhausted from
                # birth — its watermark must not pin downstream at the
                # floor while peers' shares flow. NOT under the DCN
                # exchange: there out_wm[sid] is the GLOBAL watermark
                # applied downstream (the rendezvous meta carries the
                # per-process local, already _FINAL for an empty
                # process) — pinning it to _FINAL here made a
                # zero-split process fire its windows immediately and
                # drop every routed record as late (found by the chaos
                # suite's DCN peer-death soak).
                self._out_wm[sid] = _FINAL
            d = srcs[sid] = {}
            for i in owned:
                if sid in self._dev_chains:
                    # no materialization, no feeder thread: the
                    # iterator yields per-batch metadata markers only
                    # (sub-batch markers when the chain subdivided)
                    d[i] = _dev_batch_markers(
                        self._dev_subdivided.get(sid, n.source),
                        self._positions[sid].get(i, 0))
                    continue
                it = n.source.open_split(splits[i],
                                         self._positions[sid].get(i, 0))
                d[i] = (_Prefetcher(it, depth=prefetch)
                        if prefetch > 0 else it)

        if self.plan.runtime_mode == "batch":
            return self._run_batch(job_name, srcs, drain)

        last_chk = time.time()
        prof = self.prof
        if self._dcn is not None:
            try:
                self._ingest_loop_dcn(srcs, interval_ms, job_name)
            finally:
                self._dcn.close()
                self._dcn = None
            active = {}
        else:
            active = {sid: sorted(its) for sid, its in srcs.items()}
        while any(active.values()):
            for sid, splits_alive in list(active.items()):
                if not splits_alive:
                    continue
                for split_ix in list(splits_alive):
                    if self._cancel is not None and self._cancel.is_set():
                        raise JobCancelledError(job_name)
                    it = srcs[sid][split_ix]
                    t0 = time.perf_counter()
                    nxt = next(it, None)
                    t1 = time.perf_counter()
                    prof["source_next"] += t1 - t0
                    if nxt is None:
                        splits_alive.remove(split_ix)
                        continue
                    already_sub = False
                    if isinstance(nxt, _DevBatch):
                        op = self._ops[self._dev_chains[sid]]
                        with self._link_lock:
                            pass
                        t2 = time.perf_counter()
                        prof["link_lock_wait"] += t2 - t1
                        with self._push_lock:
                            ok = op.process_batch_device(nxt.index)
                            if ok:
                                self.metrics["records_in"] += nxt.n
                                self.metrics["batches"] += 1
                        if ok:
                            # probe readiness: throttle waits cost a
                            # relay round trip each, so they amortize
                            # at LOGICAL-batch granularity — only the
                            # last sub-batch of a logical group
                            # rate-matches (the in-flight credit was
                            # scaled by the same factor in _build_ops,
                            # so depth in bytes is unchanged).
                            # Piggybacked readiness makes each wait a
                            # consume of an already-announced transfer
                            # (no extra round trip), so the throttle
                            # rate-matches at EVERY sub-batch — the
                            # credit accounting scales with the finer
                            # cadence instead of batching it.
                            f = self._sub_factor.get(sid, 1)
                            if (f == 1 or self._readiness == "piggyback"
                                    or (nxt.index + 1) % f == 0):
                                for op2 in self._ops.values():
                                    if hasattr(op2, "throttle"):
                                        op2.throttle()
                            prof["push"] += time.perf_counter() - t2
                            self._positions[sid][split_ix] += 1
                            self._eps_meter.mark(nxt.n)
                            mx = nxt.ts_max
                            self._max_ts[sid] = max(self._max_ts[sid], mx)
                            self._wm_gens[sid][split_ix].on_batch(mx)
                            self._wm_lag.set(mx - self._out_wm[sid])
                            self._check_drain_error()
                            continue
                        # a devgen gate closed for this batch (ring
                        # outgrew the header, oversized lateness span):
                        # materialize it on the host and push normally
                        # (the subdivided stream's gen yields the same
                        # bit-exact sub-batch slice — already at
                        # sub-batch size, so the host path must not
                        # slice it K ways again)
                        already_sub = self._sub_factor.get(sid, 1) > 1
                        nxt = self._dev_subdivided.get(
                            sid, self.plan.node(sid).source).gen(
                            "0", nxt.index)
                    data, ts = nxt
                    ts = np.asarray(ts, np.int64)
                    if self._sub_batches > 1 and not already_sub:
                        # sub-batch fire/emit decoupling, host plane:
                        # K equal slices, each followed by a watermark
                        # advance + fire dispatch, so fired rows reach
                        # the drain at sub-batch cadence. Position /
                        # eps / max-ts accounting stays below, at
                        # logical-batch granularity.
                        t1 = self._ingest_host_subbatched(
                            sid, split_ix, splits_alive, data, ts, t1)
                    else:
                        for data_c, ts_c in self._debloat_split(data, ts):
                            t1 = self._push_source_chunk(
                                sid, data_c, ts_c, t1)
                    self._advance_position(sid, split_ix, data, ts)
                    self._eps_meter.mark(len(ts))
                    if len(ts):
                        mx = int(ts.max())
                        self._max_ts[sid] = max(self._max_ts[sid], mx)
                        self._wm_gens[sid][split_ix].on_batch(mx)
                        self._wm_lag.set(mx - self._out_wm[sid])
                # exhausted splits stop holding the watermark back
                # (ref: idle-channel handling in the valve)
                self._recombine_source_wm(sid, splits_alive)
                t3 = time.perf_counter()
                with self._push_lock:
                    self._propagate_watermarks()
                prof["advance_wm"] += time.perf_counter() - t3
                self._check_drain_error()
            if self._profiler is not None:
                self._profiler.step()
            self._debloat_adjust()
            # operator-triggered savepoint (CLI `savepoint` command):
            # synchronous + retained, at this batch boundary
            self._maybe_take_savepoint()
            # async checkpointing: commit any finished background
            # checkpoint (never blocks), then kick off the next one when
            # the interval elapsed and no persistence is in flight
            self._complete_pending_checkpoint(wait=False, tolerate=True)
            if (self._coordinator is not None and interval_ms > 0
                    and self._ckpt_pending is None
                    and (time.time() - last_chk) * 1000 >= interval_ms):
                self._ckpt_pending = self._begin_checkpoint()
                last_chk = time.time()

        # end of input: final watermark per stateful op flushes everything.
        # Quiesce the device pipeline first (outside the push lock — the
        # drain keeps delivering) so the flush fires don't queue behind
        # in-flight ingest steps and their latency stays steady-state.
        for op in self._ops.values():
            if hasattr(op, "quiesce"):
                op.quiesce()
        for sid in self.plan.sources:
            self._out_wm[sid] = _FINAL
        with self._push_lock:
            self._propagate_watermarks(final=True)
        self._flush_emits()
        # a savepoint requested after the last batch boundary must still
        # land (bounded inputs can finish before the next loop pass)
        self._maybe_take_savepoint()
        if self._coordinator is not None and interval_ms > 0:
            self.checkpoint_now()  # final epoch commit for 2PC sinks
            # (completes any pending background checkpoint first)
        else:
            # bounded job WITHOUT checkpointing: transactional sinks
            # still owe a final commit — end of input is the terminal
            # barrier and the run either completes whole or replays
            # whole, so commit-at-end preserves exactly-once (ref:
            # StreamTask.endInput → final checkpoint committing
            # pending transactions even with checkpointing disabled).
            self._commit_final_epoch()
        return self._finish_run(job_name, drain)

    def _source_offset_committers(self):
        """One commit-round fn per source that publishes externally
        visible committed offsets (log.LogSource consumer groups):
        called with the checkpoint id AFTER the checkpoint is durable,
        with the replay positions FROZEN at this barrier — the group
        floor can never outrun the checkpoint that proves the rows
        were consumed exactly once."""
        fns = []
        for sid in self.plan.sources:
            src = self.plan.node(sid).source
            if src is None or not hasattr(src, "commit_offsets"):
                continue
            frozen = dict(self._positions.get(sid, {}))

            def _commit(cid, _src=src, _frozen=frozen):
                _src.commit_offsets(cid, _frozen)

            fns.append(_commit)
        return fns

    def _commit_final_epoch(self) -> None:
        """2PC sinks' terminal commit for a bounded run without
        checkpointing — end of input is the terminal barrier. The epoch
        id must not collide with ANY earlier run's ids in a reused sink
        directory (a replayed id silently drops this run's staged
        output as "already committed") — a ms timestamp is unique
        across runs and above any coordinator-numbered epoch. Consumer-
        group sources publish their final offsets under the same
        terminal barrier (the run completes whole or replays whole)."""
        final_epoch = int(time.time() * 1000)
        for n in self.plan.nodes.values():
            if n.kind == "sink" and hasattr(n.sink, "prepare_commit"):
                n.sink.prepare_commit(final_epoch)
                n.sink.notify_checkpoint_complete(final_epoch)
        if getattr(self, "_positions", None):
            for fn in self._source_offset_committers():
                fn(final_epoch)

    def _finish_run(self, job_name: str, drain) -> "JobResult":
        """Shared happy-path epilogue of both runtime modes: stop the
        drain, close sinks/ops/servers, fold counters into the
        JobResult."""
        from flink_tpu.api.environment import JobResult

        self._emit_q.put(None)
        drain.join()
        self._emit_q = None
        self._check_drain_error()
        for n in self.plan.nodes.values():
            if n.kind == "sink":
                n.sink.close()
        for nid, op in self._ops.items():
            if self.plan.node(nid).kind == "async_io":
                op.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
        for nid, op in self._ops.items():
            for counter in ("late_records", "records_dropped_full",
                            "exchange_overflow", "records_spilled"):
                if hasattr(op, counter):
                    self.metrics[counter] = (
                        self.metrics.get(counter, 0) + getattr(op, counter))
        final = dict(self.metrics)
        final.update(self.registry.snapshot())
        for k, v in self.prof.items():
            final[f"profile.driver.{k}"] = v
        # the per-phase breakdown (dispatch/throttle/drain/advance/fire)
        # under the ONE shared accounting (phase_breakdown) — bench
        # artifacts embed these next to profile_top_ops so control-
        # plane wins are attributed, not asserted (PROFILE.md §12)
        for k, v in self.phase_breakdown().items():
            final[f"profile.phase.{k}"] = round(v, 6)
        if self._profiler is not None:
            summary = self._profiler.close()
            if summary is not None:
                final["profile.trace_summary"] = summary
        for nid, op in self._ops.items():
            for k, v in getattr(op, "prof", {}).items():
                final[f"profile.op{nid}.{k}"] = final.get(
                    f"profile.op{nid}.{k}", 0.0) + v
        return JobResult(job_name, final)

    # -- bounded execution (execution.runtime-mode=batch) ----------------
    def _run_batch(self, job_name: str, srcs, drain) -> "JobResult":
        """Wave-ordered bounded execution (SURVEY §3.6/§3.7): stages
        run in the topological order the compiler leveled them into
        (runtime/scheduler.py BatchStageScheduler); every blocking edge
        materializes in full as columnar partition files
        (exchange/blocking.py) before its consumer starts; stateful
        operators fire exactly ONCE, at end-of-input — no per-step fire
        scans, which is the mode's entire performance case on bounded
        inputs."""
        from flink_tpu.config import ExecutionOptions
        from flink_tpu.exchange.blocking import BlockingShuffle
        from flink_tpu.runtime.scheduler import BatchStageScheduler

        cfg = self.config
        # re-execution exactly-once: a crashed prior attempt (kill -9
        # skips run()'s cleanup) may have left staged rows in reused
        # sink directories; this run must commit ONLY its own output
        self._abort_sinks()
        sched = BatchStageScheduler(self.plan)
        shuffle = BlockingShuffle(
            str(cfg.get(ExecutionOptions.BATCH_SHUFFLE_DIR)), job_name,
            n_partitions=int(cfg.get(
                ExecutionOptions.BATCH_SHUFFLE_PARTITIONS)),
            cleanup=bool(cfg.get(ExecutionOptions.BATCH_SHUFFLE_CLEANUP)))
        # every writer opens up front and stays open across waves (a
        # union may merge wave-0 and wave-1 producers into one blocking
        # edge); an edge seals exactly when its CONSUMER's wave starts —
        # by then every producer wave has finished
        for u, v in self.plan.blocking_edges:
            self._batch_capture[(u, v)] = shuffle.open_edge(
                u, v, key_field=self._edge_key_field(u, v))
        t0 = time.perf_counter()
        try:
            for stage in sched.waves:
                self._batch_reject_savepoint()
                for u, v in stage.in_edges:
                    self._batch_capture.pop((u, v)).seal()
                sched.start(stage)
                if stage.index == 0:
                    for sid in stage.heads:
                        self._batch_drain_source(sid, srcs[sid], job_name)
                else:
                    for v in stage.heads:
                        self._batch_feed_head(v, stage, shuffle,
                                              job_name)
                self._batch_finalize_wave(stage)
                sched.finish(stage)
            self.metrics["shuffle_bytes_spooled"] = shuffle.bytes_written
            self.metrics["shuffle_rows_spooled"] = shuffle.rows_spooled
            self.metrics["batch_waves"] = len(sched.waves)
            # a request armed DURING the last wave must fail too —
            # the streaming path covers this window with its post-loop
            # _maybe_take_savepoint; returning FINISHED while the
            # requester waits forever would be the silent alternative
            self._batch_reject_savepoint()
        finally:
            self._batch_capture = {}
            shuffle.close()
        self._commit_final_epoch()
        self.metrics["batch_wall_s"] = round(time.perf_counter() - t0, 3)
        return self._finish_run(job_name, drain)

    def _batch_reject_savepoint(self) -> None:
        """The runner rejects savepoint triggers for jobs without
        checkpoint storage (which batch jobs are), so only a direct
        caller can arm the request — fail loudly rather than leave the
        requester waiting on a completion that can never come."""
        if (self._savepoint_request is not None
                and self._savepoint_request.is_set()):
            raise ValueError(
                "savepoints are not supported in "
                "execution.runtime-mode=batch (nothing checkpoints; "
                "recovery is re-execution)")

    def _edge_key_field(self, u: int, v: int) -> Optional[str]:
        """Key column routing a blocking edge's partition files (None =
        single partition). Join edges key on their side's column."""
        n = self.plan.node(v)
        if n.kind == "join":
            t = n.window_transform
            return t.left_key if u == n.left_input else t.right_key
        if n.kind in ("window", "session", "count_window", "process",
                      "cep", "evicting_window", "global_agg"):
            return n.key_field
        return None  # window_all / async_io / broadcast_connect

    def _batch_drain_source(self, sid: int, d, job_name: str) -> None:
        """Wave 0: run one source's splits to exhaustion, pushing every
        batch through its stage's pipelined (stateless) chain — and
        into blocking-edge spools at the stage boundary. No watermark
        propagation per batch: time only moves at the wave finalize."""
        prof = self.prof
        for split_ix in sorted(d):
            it = d[split_ix]
            while True:
                if self._cancel is not None and self._cancel.is_set():
                    raise JobCancelledError(job_name)
                t0 = time.perf_counter()
                nxt = next(it, None)
                prof["source_next"] += time.perf_counter() - t0
                if nxt is None:
                    break
                data, ts = nxt
                ts = np.asarray(ts, np.int64)
                t1 = time.perf_counter()
                with self._push_lock:
                    self.metrics["records_in"] += len(ts)
                    self.metrics["batches"] += 1
                    self._push_downstream(
                        sid, (dict(data), ts, np.ones(len(ts), bool)))
                for op in self._ops.values():
                    if hasattr(op, "throttle"):
                        op.throttle()
                prof["push"] += time.perf_counter() - t1
                self._advance_position(sid, split_ix, data, ts)
                self._eps_meter.mark(len(ts))
                if len(ts):
                    self._max_ts[sid] = max(self._max_ts[sid],
                                            int(ts.max()))
                self._check_drain_error()
        self._out_wm[sid] = _FINAL

    def _batch_feed_head(self, v: int, stage, shuffle,
                         job_name: str) -> None:
        """Replay a stage head's sealed input partitions into the
        operator. Broadcast state builds fully before the main input
        (the batch BroadcastState discipline); join feeds left then
        right (watermark-blind until the wave finalize, so side order
        is semantics-free)."""
        n = self.plan.node(v)
        # the scheduler's in_edges are the single source of truth for
        # which partitions exist (the seal loop used the same list)
        edges = [(u2, v2) for u2, v2 in stage.in_edges if v2 == v]
        if n.kind == "broadcast_connect":
            edges.sort(key=lambda e: 0 if e[0] == n.right_input else 1)
        elif n.kind == "join":
            edges.sort(key=lambda e: 0 if e[0] == n.left_input else 1)
        op = self._ops.get(v)
        for u, _ in edges:
            for data, ts in shuffle.edge(u, v).read():
                if self._cancel is not None and self._cancel.is_set():
                    raise JobCancelledError(job_name)
                t1 = time.perf_counter()
                with self._push_lock:
                    self.metrics["shuffle_records_replayed"] = (
                        self.metrics.get("shuffle_records_replayed", 0)
                        + len(ts))
                    self._push(v, (data, ts, np.ones(len(ts), bool)),
                               from_node=u)
                    if n.kind == "async_io":
                        # keep enrichment results flowing mid-stage —
                        # nothing else polls between wave finalizes
                        for b in op.poll():
                            self._push_downstream(v, b)
                for o in self._ops.values():
                    if hasattr(o, "throttle"):
                        o.throttle()
                self.prof["push"] += time.perf_counter() - t1
                self._check_drain_error()

    def _batch_finalize_wave(self, stage) -> None:
        """End-of-input for one wave: quiesce its device pipelines,
        then ONE final watermark pass over exactly this wave's nodes —
        the single fire scan of the whole bounded run for each stateful
        op — and barrier the emit drain so fires are fully delivered
        (and captured into downstream blocking edges) before the wave
        is declared finished."""
        only = set(stage.nodes)
        for nid in only:
            op = self._ops.get(nid)
            if op is not None and hasattr(op, "quiesce"):
                op.quiesce()
        with self._push_lock:
            self._propagate_watermarks(final=True, only=only)
        self._flush_emits()

    def _push_source_chunk(self, sid: int, data_c, ts_c,
                           t1: float) -> float:
        """Push ONE ingest chunk downstream (the hot-loop body shared
        by the plain and sub-batched paths): link-quiet handshake,
        locked push + metrics, backpressure wait OUTSIDE the lock.
        Returns the next chunk's profiling anchor."""
        prof = self.prof
        valid = np.ones(len(ts_c), bool)
        # yield the transport to a drain fetch in progress (see
        # _link_lock): blocks only while one is active
        with self._link_lock:
            pass
        t2 = time.perf_counter()
        prof["link_lock_wait"] += t2 - t1
        with self._push_lock:
            self.metrics["records_in"] += len(ts_c)
            self.metrics["batches"] += 1
            self._push_downstream(sid, (dict(data_c), ts_c, valid))
        # backpressure wait OUTSIDE the lock: the drain thread must be
        # able to deliver while ingest blocks on the device pipeline
        for op in self._ops.values():
            if hasattr(op, "throttle"):
                op.throttle()
        prof["push"] += time.perf_counter() - t2
        return time.perf_counter()

    def _recombine_source_wm(self, sid: int, splits_alive) -> None:
        """Source watermark = min over ALIVE split generators (a
        lagging split must hold it back); exhausted splits drop out.
        Combines run over OWNED splits only — an enumerator-assigned
        subset must not let never-advancing foreign splits pin the
        watermark at the floor."""
        gens = [self._wm_gens[sid][i] for i in splits_alive]
        owned = self._owned_splits.get(sid) or []
        if gens:
            self._out_wm[sid] = min(g.current() for g in gens)
        elif owned:
            self._out_wm[sid] = min(
                self._wm_gens[sid][i].current() for i in owned)

    def _ingest_host_subbatched(self, sid: int, split_ix: int,
                                splits_alive, data, ts,
                                t1: float) -> float:
        """Host-plane sub-batching (pipeline.sub-batches = K > 1): the
        logical batch is pushed as K equal slices, and after EACH slice
        the watermark clock advances and fires dispatch — a fired
        window's rows become host-visible at sub-batch cadence instead
        of waiting out the whole logical batch. Record order is
        untouched (slices are contiguous), so watermark semantics and
        committed rows match the K=1 run; only fire GROUPING is finer.
        Position advance and throughput accounting stay with the
        caller, at logical-batch granularity."""
        prof = self.prof
        n = len(ts)
        sub = max(1, -(-n // self._sub_batches))  # ceil: ragged tails
        gens = self._wm_gens[sid]
        for lo in range(0, n, sub):
            hi = min(lo + sub, n)
            data_s = {k: v[lo:hi] for k, v in data.items()}
            ts_s = ts[lo:hi]
            for data_c, ts_c in self._debloat_split(data_s, ts_s):
                t1 = self._push_source_chunk(sid, data_c, ts_c, t1)
            if len(ts_s):
                gens[split_ix].on_batch(int(ts_s.max()))
            self._recombine_source_wm(sid, splits_alive)
            t3 = time.perf_counter()
            with self._push_lock:
                self._propagate_watermarks()
            prof["advance_wm"] += time.perf_counter() - t3
            self._check_drain_error()
        return t1

    def _advance_position(self, sid: int, split_ix: int, data, ts) -> None:
        """One consumed source batch: the SOURCE defines what the next
        replay position is (api/sources.py position_after — batch
        count by default; record OFFSETS for offset-addressed sources
        like log.LogSource, so a restore resumes mid-partition)."""
        src = self.plan.node(sid).source
        pos = self._positions[sid][split_ix]
        self._positions[sid][split_ix] = src.position_after(pos, data, ts)

    # -- data plane ------------------------------------------------------
    def phase_breakdown(self) -> Dict[str, float]:
        """Cumulative per-phase wall seconds of this run — ONE
        accounting shared by the bench artifacts (per-trial
        ``phase_breakdown``), the JobResult (``profile.phase.*``), and
        the web-UI backpressure gauge, so the §8.3 cost attribution
        (throttle / drain / advance / fire) is measured the same way
        everywhere instead of each consumer summing its own subset.

        Phases (best-effort attribution from the always-on prof
        accumulators, clamped non-negative):
          source   — source iterator next() (decode/generate)
          dispatch — ingest push + device-step dispatch, MINUS the
                     throttle share accrued inside it (push timing
                     wraps the throttle loop)
          throttle — backpressure waits (pb_throttle_wait)
          drain    — emit-ring/pack fetch time: the drain thread's
                     link-held window plus ring fetches made outside
                     it (the sync spill drain runs on the loop thread)
          advance  — watermark-advance bookkeeping minus the fire
                     dispatch it wraps
          fire     — fire-path dispatch inside advance_watermark
                     (aw_dispatch)"""
        def opsum(key: str) -> float:
            return sum(getattr(op, "prof", {}).get(key, 0.0)
                       for op in self._ops.values())

        prof = self.prof
        throttle = opsum("pb_throttle_wait")
        fire = opsum("aw_dispatch")
        drain_thread = prof.get("drain_link_held", 0.0)
        # drain_fetch accrues inside the drain thread's link window on
        # the async path; count only the excess (sync drains on the
        # loop thread) so the two never double-count
        drain = drain_thread + max(0.0, opsum("drain_fetch") - drain_thread)
        return {
            "source": prof.get("source_next", 0.0),
            "dispatch": max(0.0, prof.get("push", 0.0)
                            + prof.get("link_lock_wait", 0.0) - throttle),
            "throttle": throttle,
            "drain": drain,
            "advance": max(0.0, prof.get("advance_wm", 0.0) - fire),
            "fire": fire,
        }

    def live_metrics(self) -> Dict[str, Any]:
        """Racy-read live counters for the heartbeat-carried job
        metrics (cluster web UI gauges; ref: the TaskManager metric
        report feeding the REST vertices/backpressure endpoints)."""
        ph = self.phase_breakdown()
        # the gauges read the SAME phase accounting as the artifacts
        # (phase_breakdown), split per THREAD so each busy fraction is
        # a share of one thread's wall: backpressure = the INGEST
        # loop's waits (throttle + advance bookkeeping — pre-§12 only
        # pb_throttle_wait, so advance stalls were invisible); the
        # drain thread's link-held time is its own gauge — folding it
        # into the ingest fraction would read ~100% backpressure on a
        # healthy pipeline whose drain merely holds the link.
        tw = ph["throttle"] + ph["advance"]
        dw = ph["drain"]
        now = time.perf_counter()
        last_t, last_w, last_d = getattr(
            self, "_lm_prev", (now - 1e-9, tw, dw))
        self._lm_prev = (now, tw, dw)
        # DELTA busy fraction since the previous sample — a cumulative
        # counter over heartbeat age would peg at 100% forever
        span = max(now - last_t, 1e-9)
        bp = max(0.0, min(1.0, (tw - last_w) / span))
        dp = max(0.0, min(1.0, (dw - last_d) / span))
        out: Dict[str, Any] = {
            "records_in": int(self.metrics.get("records_in", 0)),
            "records_out": int(self.metrics.get("records_out", 0)),
            "fired_windows": int(self.metrics.get("fired_windows", 0)),
            "eps": round(self._eps_meter.rate, 1),
            "wm_lag_ms": float(getattr(self._wm_lag, "value", 0.0) or 0),
            "backpressure_pct": round(100 * bp),
            "drain_busy_pct": round(100 * dp),
        }
        if self._coordinator is not None:
            # in-memory stats, NOT a storage listing: this runs on the
            # heartbeat thread every beat — filesystem I/O here could
            # stall liveness on a slow checkpoint store
            out["checkpoints"] = [
                {"id": st.checkpoint_id, "ts": st.trigger_ts_ms,
                 "bytes": st.size_bytes}
                for st in self._coordinator.stats[-3:]]
        return out

    def _push_downstream(self, nid: int, batch: Batch) -> None:
        for d in self.plan.node(nid).downstream:
            self._push(d, batch, from_node=nid)

    def _push(self, nid: int, batch: Batch, from_node: int) -> None:
        if self._batch_capture:
            # bounded mode: a blocking edge diverts into its shuffle
            # spool — the consumer sees nothing until its wave replays
            # the sealed partition files (SURVEY §3.7)
            w = self._batch_capture.get((from_node, nid))
            if w is not None:
                w.write(*batch)
                return
        n = self.plan.node(nid)
        data, ts, valid = batch
        if n.kind == "chain":
            for fn in n.fns:
                data, ts, valid = fn(data, ts, valid)
            self._push_downstream(nid, (data, ts, valid))
        elif n.kind == "union":
            self._push_downstream(nid, batch)
        elif n.kind == "async_io":
            op = self._ops[nid]
            ups = self._upstream[nid]
            in_wm = min((self._out_wm[u] for u in ups), default=LONG_MIN)
            op.submit(batch, in_wm)
        elif n.kind == "partition":
            # single local driver = parallelism 1: every strategy is a
            # pass-through here (identical to the reference at p=1). The
            # subtask assignment still runs so round-robin cursors and
            # shuffle streams advance deterministically — the state the
            # multi-runner scheduler consumes (exchange/partitioners.py)
            part = self._partitioners.get(nid)
            if part is None:
                from flink_tpu.exchange.partitioners import make_partitioner

                # node-id seed: stacked shuffles must not correlate
                part = self._partitioners[nid] = make_partitioner(
                    n.partition_strategy, seed=nid)
            if not part.broadcast:
                part.advance(len(batch[1]), 1)  # no allocation at p=1
            self._push_downstream(nid, batch)
        elif n.kind == "window_all":
            op = self._ops[nid]
            dev_data = {k: v for k, v in data.items()
                        if np.asarray(v).dtype != object}
            op.process_batch(ts, dev_data, valid)
        elif n.kind in ("window", "session", "count_window", "process",
                        "cep", "evicting_window", "global_agg"):
            op = self._ops[nid]
            keys = np.asarray(data[n.key_field], np.int64)
            dev_data = {k: v for k, v in data.items()
                        if np.asarray(v).dtype != object}
            op.process_batch(keys, ts, dev_data, valid)
            if n.kind in ("count_window", "process", "cep",
                          "evicting_window", "global_agg", "session"):
                # these emit per-step, not (only) per-watermark
                # (session: retract-mode -U rows from merges that
                # consumed an already-fired span)
                fired = op.take_fired()
                if fired is not None:
                    self._emit_fired(nid, fired)
        elif n.kind == "join":
            op = self._ops[nid]
            t = n.window_transform
            if from_node == n.left_input:
                keys = np.asarray(data[t.left_key], np.int64)
                op.process_left(keys, ts, data, valid)
            else:
                keys = np.asarray(data[t.right_key], np.int64)
                op.process_right(keys, ts, data, valid)
        elif n.kind == "broadcast_connect":
            op = self._ops[nid]
            if from_node == n.right_input:
                op.process_broadcast(ts, data, valid)
            else:
                op.process_main(ts, data, valid)
            fired = op.take_fired()
            if fired is not None:
                self._emit_fired(nid, fired)
        elif n.kind == "sink":
            compact = {k: v[valid] for k, v in data.items()}
            nrec = int(valid.sum())
            if nrec:
                self.metrics["records_out"] += nrec
                n.sink.write(compact)
        else:
            raise AssertionError(f"unroutable node kind {n.kind}")

    # -- time plane ------------------------------------------------------
    def _propagate_watermarks(self, final: bool = False,
                              only=None) -> None:
        """Advance node watermarks in topo order (the StatusWatermarkValve
        min-over-inputs rule applied at node granularity, ref: streaming/
        runtime/watermarkstatus/StatusWatermarkValve.java).

        ``only``: restrict to a node-id set — the batch runtime's
        per-wave finalize (a later wave's still-empty operators must
        not see a final watermark before their input stage ran)."""
        for nid in self.plan.topo_order:
            if only is not None and nid not in only:
                continue
            n = self.plan.node(nid)
            if n.kind == "source":
                continue
            ups = self._upstream[nid]
            in_wm = min(self._out_wm[u] for u in ups) if ups else LONG_MIN
            # count_window is deliberately absent: it is event-time-blind
            # (fires ride process_batch), so advancing it would only
            # queue guaranteed-empty fires through the drain
            if n.kind in ("window", "session", "join", "window_all",
                          "process", "evicting_window"):
                op = self._ops[nid]
                if getattr(op, "uses_processing_time", False):
                    # proc-time windows: the clock, not the event
                    # watermark, drives fires; end of input drains
                    # (fires everything seen — the stop-with-drain
                    # semantics of the reference)
                    if in_wm == _FINAL or final:
                        fired = op.advance_watermark(op.final_watermark())
                    else:
                        fired = op.advance_processing_time()
                    self._emit_fired(nid, fired)
                    self._out_wm[nid] = in_wm
                    continue
                wm = in_wm
                if in_wm == _FINAL:
                    wm = op.final_watermark()
                if wm > op.watermark or final:
                    fired = op.advance_watermark(wm)
                    self._emit_fired(nid, fired)
                # processing-time TIMERS (KeyedProcessFunction) fire on
                # the clock alongside the event-time advance
                adv_proc = getattr(op, "advance_processing_time_timers",
                                   None)
                if adv_proc is not None:
                    fired2 = adv_proc(fire_all=(in_wm == _FINAL or final))
                    if fired2 is not None:
                        self._emit_fired(nid, fired2)
                self._out_wm[nid] = in_wm
            elif n.kind == "async_io":
                op = self._ops[nid]
                final_in = in_wm == _FINAL
                if not final_in:
                    op.note_watermark(in_wm)
                for b in op.poll(drain=final_in):
                    self._push_downstream(nid, b)
                # a watermark must never overtake buffered batches
                self._out_wm[nid] = _FINAL if final_in else op.watermark
            else:
                self._out_wm[nid] = in_wm

    def _emit_fired(self, nid: int, fired) -> None:
        """Route fired windows downstream. When the downstream subtree is
        stateless (chains/sinks only), materialization happens on the
        drain thread — the device→host fetch leaves the hot loop, the
        way the reference hands buffers to Netty's IO thread off the
        mailbox thread (ref: PipelinedSubpartition.notifyDataAvailable).
        Stateful downstream (a second window stage) keeps the in-line
        path so operator state is touched by one thread only."""
        if self._emit_q is not None and self._stateless_downstream(nid):
            self._emit_q.put((nid, fired, time.time()))
            return
        self._emit_fired_sync(nid, fired, time.time())

    def _emit_fired_sync(self, nid: int, fired, stamp: float) -> None:
        ring_origin = getattr(fired, "_ring", False)
        out = dict(fired)  # materializes lazy FiredWindows
        if ring_origin:
            # emit-ring fires: one latency sample PER FIRE COHORT whose
            # rows this drain made host-visible, stamped NOW (delivery)
            # against each cohort's own dispatch time. The per-batch
            # sample below would attribute every coalesced sub-batch
            # fire to the OLDEST queue item's stamp — overstating p99
            # exactly when sub-batching improves it.
            self._note_ring_latency(nid)
        if "__ts__" in out:
            # process-function emissions: explicit per-row timestamps
            ts = np.asarray(out.pop("__ts__"), np.int64)
            nrec = len(ts)
        else:
            nrec = len(out.get("window_end", ()))  # windowed schemas
            # (keyed rows also carry "key"; windowAll rows don't)
            ts = (np.asarray(out["window_end"], np.int64) - 1
                  if nrec else np.zeros(0, np.int64))
        if nrec == 0:
            return
        self.metrics["fired_windows"] += nrec
        valid = np.ones(nrec, bool)
        self._push_downstream(nid, (out, ts, valid))
        # latency marker: watermark-advance dispatch → delivered at sink
        # (ref: streaming/runtime/streamrecord/LatencyMarker.java)
        if not ring_origin:
            self._lat_hist.update((time.time() - stamp) * 1000.0)

    def _note_ring_latency(self, nid: int) -> None:
        op = self._ops.get(nid)
        take = getattr(op, "take_delivered_fire_stamps", None)
        if take is None:
            return
        now = time.time()
        for fire_stamp in take():
            self._lat_hist.update((now - fire_stamp) * 1000.0)

    def _stateless_downstream(self, nid: int) -> bool:
        """True iff nothing stateful (window/session/join) is reachable
        below nid — the async-drain safety condition."""
        if nid not in self._stateless_cache:
            seen = set()
            stack = list(self.plan.node(nid).downstream)
            ok = True
            while stack:
                d = stack.pop()
                if d in seen:
                    continue
                seen.add(d)
                # STAGE_HEAD_KINDS is the authoritative stateful set —
                # a stateful node below must keep fires on the loop
                # thread (single-writer operator state)
                if self.plan.node(d).kind in STAGE_HEAD_KINDS:
                    ok = False
                    break
                stack.extend(self.plan.node(d).downstream)
            self._stateless_cache[nid] = ok
        return self._stateless_cache[nid]

    def _drain_entry(self) -> None:
        """Drain-thread trampoline: carries the job's fault scope (a
        session tenant's scoped plan must see this thread as the job's)
        and the fair-drain gate membership across the loop's lifetime."""
        from flink_tpu import faults

        gate = self._drain_gate
        if gate is not None:
            gate.register(self._gate_token)
        try:
            with faults.job_scope(getattr(self, "_fault_scope", None)):
                self._drain_loop()
        finally:
            if gate is not None:
                gate.unregister(self._gate_token)

    def _drain_loop(self) -> None:
        import contextlib
        import queue as _q

        from flink_tpu.ops.window import FiredWindows

        # local refs: an abandoned (timed-out) drain must keep operating
        # on ITS queue and ITS discard cell even after run() nulls
        # self._emit_q / re-arms for a successor run
        emit_q = self._emit_q
        discard = self._drain_discard
        gate = self._drain_gate
        while True:
            items = [emit_q.get()]
            # Deferral: the fire dispatch already issued copy_to_host_async
            # on its buffers; letting the batch age lets that background
            # copy finish, so the device_get below is a local read instead
            # of a blocking round trip (decisive on remote-attached
            # accelerators where a sync fetch costs ~100ms latency).
            # A pending barrier (_flush_req) cancels the wait instantly.
            if self._emit_defer_s > 0 and items[0] is not None:
                wait = self._emit_defer_s - (time.time() - items[0][2])
                if wait > 0:
                    self._flush_req.wait(wait)
            # opportunistically take the whole backlog: N queued fires
            # materialize in ONE device→host round trip instead of N
            while True:
                try:
                    items.append(emit_q.get_nowait())
                except _q.Empty:
                    break
            stop = any(i is None for i in items)
            # aborted run: the attempt's output must never reach sinks —
            # a later attempt may reuse them (exactly-once would break)
            batch = ([] if discard[0]
                     else [i for i in items if i is not None])
            # barrier batches (job end, checkpoint flush) must fetch
            # every enqueued row; periodic ones fetch whatever announced
            # ring copy has landed and leave the rest to the next poll.
            # Read the flag BEFORE materializing: _flush_emits closes
            # the set-after-read race with a second pinned-marker pass.
            barrier = stop or self._flush_req.is_set()
            try:
                tm0 = time.perf_counter()
                # fair-drain turn: the device fetch — the part that
                # holds the shared device→host link — waits its round-
                # robin turn among co-resident jobs; the host-side
                # decode/push below stays outside the turn
                with (gate.turn(self._gate_token) if gate is not None
                      else contextlib.nullcontext()):
                    with self._link_lock:
                        FiredWindows.materialize_many(
                            [f for _, f, _ in batch], barrier=barrier)
                self.prof["drain_link_held"] += time.perf_counter() - tm0
                with self._push_lock:
                    # re-check under the push lock: the run may have
                    # aborted (and aborted the sinks) while this batch
                    # was wedged in the device fetch above — delivering
                    # it now would pollute a successor attempt's sinks
                    if not discard[0]:
                        for nid, fired, stamp in batch:
                            self._emit_fired_sync(nid, fired, stamp)
            except BaseException as e:  # surface at the next barrier —
                # a silently-dead drain thread would deadlock join()
                self._drain_error = e
                for _ in items:
                    emit_q.task_done()
                # keep consuming so task_done accounting stays balanced
                while True:
                    it = emit_q.get()
                    emit_q.task_done()
                    if it is None:
                        return
            else:
                for _ in items:
                    emit_q.task_done()
            if stop:
                return

    def _check_drain_error(self) -> None:
        if self._drain_error is not None:
            e = self._drain_error
            self._drain_error = None
            raise e

    def _flush_emits(self) -> None:
        """Barrier: all enqueued fires fully delivered (checkpoint
        consistency + end-of-job ordering). Cancels the drain deferral
        for anything in flight."""
        if self._emit_q is not None:
            self._flush_req.set()
            try:
                self._emit_q.join()
                # a drain batch already in flight when the flag was set
                # may have materialized as a periodic (non-barrier)
                # poll, leaving announced-but-unfetched ring rows on
                # device. Requeue one marker per ring operator pinned at
                # its CURRENT version; the flag is still set, so this
                # second pass drains everything.
                from flink_tpu.ops.window import FiredWindows
                extra = False
                for nid, op in self._ops.items():
                    no = getattr(op, "_ring_version_no", 0)
                    if no and getattr(op, "_emit_ring", None) is not None:
                        self._emit_q.put(
                            (nid, FiredWindows(op=op, ring=True, ring_no=no),
                             time.time()))
                        extra = True
                if extra:
                    self._emit_q.join()
            finally:
                self._flush_req.clear()
        self._check_drain_error()


@dataclasses.dataclass
class _DcnStepState:
    """Per-run mutable state of the cross-host step loop, threaded
    through ``_dcn_consume_step`` so the overlapped and lockstep paths
    share one consume implementation."""

    last_chk: float = 0.0
    pending: Any = None     # persisted-but-uncommitted checkpoint
    pending_id: int = -1
    persisted_id: int = -1  # newest id THIS process holds durably


class _DevBatch:
    """Per-batch metadata marker of a device-chained generator source:
    the batch itself is synthesized on the accelerator; the host loop
    only needs its index, record count, and exact ts bounds (for the
    watermark clock and metrics)."""

    __slots__ = ("index", "ts_min", "ts_max", "n")

    def __init__(self, index: int, ts_min: int, ts_max: int, n: int):
        self.index = index
        self.ts_min = ts_min
        self.ts_max = ts_max
        self.n = n


def _dev_batch_markers(src, start: int):
    for i in range(start, src.n_batches):
        tmin, tmax = src.ts_bounds(i)
        yield _DevBatch(i, tmin, tmax, src.batch_size)


class _Prefetcher:
    """Pulls source batches ahead on a feeder thread so record
    generation/decode overlaps the main loop's keying + h2d + dispatch
    work (ref: the FLIP-27 SourceReader's split-fetcher threads,
    runtime/source — IO off the processing thread). Exceptions from the
    source surface on the consuming side, at the batch where they
    occurred."""

    def __init__(self, it, depth: int = 2) -> None:
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._it = it
        self._done = False
        self._closed = False
        self._thread = threading.Thread(target=self._feed, daemon=True)
        self._thread.start()

    def _feed(self) -> None:
        try:
            for item in self._it:
                if self._closed:
                    return
                self._q.put(item)
                if self._closed:
                    return
            self._q.put(StopIteration())
        except BaseException as e:  # surfaced on consume
            self._q.put(e)

    def close(self) -> bool:
        """Unblock and join the feeder (failed-run cleanup: a feeder
        left blocked on its full queue would leak one thread + its
        buffered batches per attempt). Returns False when the feeder is
        still alive after a bounded wait — e.g. blocked inside the
        source iterator itself, where only its own completion (gated on
        ``_closed``) can end it; it stays a daemon and delivers nowhere."""
        self._closed = True
        self._done = True
        while True:  # empty the queue so a blocked put() completes
            try:
                self._q.get_nowait()
            except Exception:
                break
        self._thread.join(timeout=1.0)
        # a wrapped iterator with its OWN background work (LogSource
        # segment readahead) must be closed through this prefetcher,
        # or its feeder thread outlives the attempt
        inner_close = getattr(self._it, "close", None)
        if inner_close is not None:
            try:
                inner_close()
            except ValueError:
                pass  # a plain generator still executing on the
                # feeder thread refuses close(); the feeder is ending
                # anyway (_closed is set)
        return not self._thread.is_alive()

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if isinstance(item, StopIteration):
            self._done = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._done = True
            raise item
        return item


def _rebase_position(pos: int, old_f: int, new_f: int, *,
                     sid: int = 0, split_ix: int = 0) -> int:
    """Convert a source replay position between sub-batch factors: a
    position counted in old_f sub-batches per logical batch becomes the
    equivalent count in new_f units. Only positions on a common
    sub-batch boundary convert (a checkpoint cut mid-logical-batch at
    K=4 cannot resume at K=3) — misalignment fails loudly rather than
    silently replaying a partial logical batch."""
    scaled = pos * new_f
    if scaled % old_f:
        raise ValueError(
            f"checkpoint position {pos} of source {sid} split "
            f"{split_ix} was taken at sub-batch factor {old_f} and "
            f"does not align to factor {new_f} — restore with the "
            "original pipeline.sub-batches, or from a logical-batch-"
            "aligned checkpoint")
    return scaled // old_f


_FINAL = np.iinfo(np.int64).max  # end-of-input marker watermark
