"""Record batches — the unit of data flow.

The reference moves one ``StreamRecord`` at a time through
deserializers and operator calls (ref: flink-core/.../api/common/typeutils/
TypeSerializer.java; streaming/runtime/streamrecord/StreamRecord.java).
A TPU cannot afford per-record dispatch: the unit here is a fixed-size
**microbatch** laid out as a struct-of-arrays pytree so every field is a
dense ``(B,)`` array the MXU/VPU can chew on, with a validity mask instead
of a dynamic length (static shapes keep XLA happy).

Schema  ≈ TypeInformation (ref: api/common/typeinfo/TypeInformation.java)
RecordBatch ≈ a buffer's worth of StreamRecords after deserialization.
Strings never reach the device: the host codec hashes/dictionary-encodes
them to int64 ids (ref: the PyFlink Cython coders play this role,
flink-python/pyflink/fn_execution/coder_impl_fast.pyx).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Timestamps are epoch milliseconds, int64 — same convention as the
# reference (StreamRecord.timestamp). MIN_TS marks "no timestamp".
TS_DTYPE = np.int64
MIN_TS = np.int64(np.iinfo(np.int64).min)

# -- changelog plane: RowKind as a small-int lane -------------------------
# ref: org.apache.flink.types.RowKind — the op type of a changelog row.
# The reference carries it as a header byte on every StreamRecord; here
# it is an ordinary int8 data column (``__op__``) that exists ONLY on
# changelog streams (retract-mode unwindowed aggregation, session-merge
# refires). Insert-only streams carry no ``__op__`` column at all, so
# the plane costs nothing until a retract-producing op creates it.
OP_FIELD = "__op__"
OP_DTYPE = np.int8
OP_INSERT = 0         # +I  first result for its key
OP_UPDATE_BEFORE = 1  # -U  retraction of the previously emitted row
OP_UPDATE_AFTER = 2   # +U  the replacement row
OP_DELETE = 3         # -D  final deletion for its key
OP_NAMES = ("+I", "-U", "+U", "-D")

# RowKind → accumulation sign: +1 for rows that ADD to a downstream
# fold (+I/+U), -1 for rows that SUBTRACT (-U/-D). Kept as a lookup
# table so both host (numpy) and device (jax take) use the same map.
_OP_SIGNS = (1, -1, 1, -1)


def op_sign(ops) -> np.ndarray:
    """(B,) accumulation signs of an ``__op__`` column (host side)."""
    return np.asarray(_OP_SIGNS, np.int64)[np.asarray(ops, np.int64)]


def is_retraction(ops) -> np.ndarray:
    """(B,) bool — True for -U/-D rows (host side)."""
    ops = np.asarray(ops, np.int64)
    return (ops == OP_UPDATE_BEFORE) | (ops == OP_DELETE)


@dataclasses.dataclass(frozen=True)
class Schema:
    """Named, fixed-dtype record fields (ref: TypeInformation extraction,
    api/java/typeutils/TypeExtractor.java — here schemas are explicit, not
    reflected, because device layouts must be static)."""

    fields: Tuple[Tuple[str, Any], ...]  # (name, numpy dtype)

    @classmethod
    def of(cls, **fields: Any) -> "Schema":
        return cls(tuple((k, np.dtype(v)) for k, v in fields.items()))

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.fields)

    def dtype(self, name: str) -> Any:
        for n, d in self.fields:
            if n == name:
                return d
        raise KeyError(name)

    def with_field(self, name: str, dtype: Any) -> "Schema":
        return Schema(self.fields + ((name, np.dtype(dtype)),))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RecordBatch:
    """A fixed-capacity microbatch of records as struct-of-arrays.

    data: field name → (B,) array.
    timestamps: (B,) int64 event times.
    valid: (B,) bool — padding mask (False rows are holes, never data).
    """

    data: Dict[str, jax.Array]
    timestamps: jax.Array
    valid: jax.Array

    # -- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        names = tuple(sorted(self.data))
        children = tuple(self.data[n] for n in names) + (self.timestamps, self.valid)
        return children, names

    @classmethod
    def tree_unflatten(cls, names, children):
        *field_vals, timestamps, valid = children
        return cls(dict(zip(names, field_vals)), timestamps, valid)

    # -- construction ----------------------------------------------------
    @classmethod
    def from_numpy(
        cls,
        data: Mapping[str, np.ndarray],
        timestamps: np.ndarray,
        valid: Optional[np.ndarray] = None,
        capacity: Optional[int] = None,
    ) -> "RecordBatch":
        """Build from host arrays, padding up to ``capacity``."""
        n = len(timestamps)
        cap = capacity or n
        if n > cap:
            raise ValueError(f"{n} records exceed capacity {cap}")
        v = np.ones(n, dtype=bool) if valid is None else np.asarray(valid, dtype=bool)
        out: Dict[str, np.ndarray] = {}
        for name, arr in data.items():
            arr = device_cast(arr)
            if len(arr) != n:
                raise ValueError(f"field {name}: length {len(arr)} != {n}")
            out[name] = _pad(arr, cap)
        return cls(
            data={k: jnp.asarray(a) for k, a in out.items()},
            timestamps=jnp.asarray(_pad(np.asarray(timestamps, dtype=TS_DTYPE), cap)),
            valid=jnp.asarray(_pad(v, cap)),
        )

    @classmethod
    def empty(cls, schema: Schema, capacity: int) -> "RecordBatch":
        return cls(
            data={n: jnp.zeros((capacity,), dtype=d) for n, d in schema.fields},
            timestamps=jnp.full((capacity,), MIN_TS, dtype=TS_DTYPE),
            valid=jnp.zeros((capacity,), dtype=bool),
        )

    # -- views -----------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def num_valid(self) -> jax.Array:
        return jnp.sum(self.valid)

    def field(self, name: str) -> jax.Array:
        return self.data[name]

    def with_data(self, **updates: jax.Array) -> "RecordBatch":
        return RecordBatch({**self.data, **updates}, self.timestamps, self.valid)

    def mask(self, keep: jax.Array) -> "RecordBatch":
        """Narrow validity (filter): rows stay in place, holes appear."""
        return RecordBatch(self.data, self.timestamps, self.valid & keep)

    def to_numpy(self) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
        return (
            {k: np.asarray(v) for k, v in self.data.items()},
            np.asarray(self.timestamps),
            np.asarray(self.valid),
        )

    def compacted_rows(self) -> Dict[str, np.ndarray]:
        """Host-side: drop padding, return only valid rows (sink path)."""
        data, ts, valid = self.to_numpy()
        out = {k: v[valid] for k, v in data.items()}
        out["__ts__"] = ts[valid]
        return out


def device_cast(arr: np.ndarray) -> np.ndarray:
    """Cast host arrays to device-safe dtypes: float64 → float32 (TPU has
    no f64); integer widths are preserved (s64 is supported)."""
    arr = np.asarray(arr)
    if arr.dtype == np.float64:
        return arr.astype(np.float32)
    return arr


def _pad(arr: np.ndarray, cap: int) -> np.ndarray:
    if len(arr) == cap:
        return arr
    pad_val = MIN_TS if arr.dtype == TS_DTYPE else 0
    out = np.full((cap,) + arr.shape[1:], pad_val, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


# ---------------------------------------------------------------------------
# Key hashing — the keyBy routing function.
# ---------------------------------------------------------------------------

def hash_keys_device(keys: jax.Array) -> jax.Array:
    """64-bit mix of integer keys, on device (traceable).

    The reference routes by murmur(key.hashCode()) → key group (ref:
    runtime/state/KeyGroupRangeAssignment.assignToKeyGroup). Here the
    same role is a splitmix64 finalizer — cheap on the VPU, good
    avalanche so ``hash % num_shards`` spreads hot key spaces.
    """
    x = keys.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> 31)
    return x.astype(jnp.int64) & jnp.int64(0x7FFFFFFFFFFFFFFF)


def hash_keys_numpy(keys: np.ndarray) -> np.ndarray:
    """Same mix on host — MUST stay bit-identical to hash_keys_device
    (host routes at ingest; device routes at in-step keyBy). Large
    batches take the C path when the codec library is built (parity
    asserted in tests); the numpy mix below is the fallback and the
    reference definition."""
    if len(keys) >= 4096:
        from flink_tpu.native_codec import hash_keys_native

        out = hash_keys_native(np.ascontiguousarray(keys, np.int64))
        if out is not None:
            return out
    with np.errstate(over="ignore"):
        x = keys.astype(np.uint64)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return (x & np.uint64(0x7FFFFFFFFFFFFFFF)).astype(np.int64)


def hash_string_key(s: str) -> int:
    """Stable 63-bit FNV-1a for string keys, host side (strings never go
    to device; ref role: StringSerializer + key-group hash)."""
    h = np.uint64(0xCBF29CE484222325)
    with np.errstate(over="ignore"):
        for b in s.encode("utf-8"):
            h = np.uint64(h ^ np.uint64(b)) * np.uint64(0x100000001B3)
    return int(h & np.uint64(0x7FFFFFFFFFFFFFFF))
