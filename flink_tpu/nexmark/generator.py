"""NEXMark event generators — the benchmark workload source.

ref: the Nexmark benchmark suite the reference is measured against
(BASELINE.json configs 1-3; upstream queries live in the external
nexmark/nexmark repo — semantics validated against the published query
definitions: Q5 hot items, Q7 highest bid, Q8 monitor new users).

Event model (numeric-only — strings are dictionary ids, SURVEY §8.4
item 7): PERSON(id, state_id), AUCTION(id, seller, category, reserve),
BID(auction, bidder, price). Proportions follow the classic NEXMark
1 person : 3 auctions : 46 bids mix. Generation is vectorized numpy and
deterministic in (split, batch_index) — the replayable-source contract
checkpoint/resume depends on.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from flink_tpu.api.sources import GeneratorSource

PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
TOTAL_PROPORTION = PERSON_PROPORTION + AUCTION_PROPORTION + BID_PROPORTION

# hot-key skew knobs (ref: nexmark generator config hotAuctionRatio etc.)
HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100
HOT_SELLER_RATIO = 100


@dataclasses.dataclass(frozen=True)
class NexmarkConfig:
    batch_size: int = 8192
    n_batches: int = 100
    events_per_ms: int = 100       # event-time density
    n_splits: int = 1
    num_active_auctions: int = 1000
    num_active_people: int = 500
    hot_ratio: int = 2             # 1/hot_ratio of bids go to hot auctions


# Declared record schemas (field -> numpy dtype name) of the three
# event streams -- seeds the plan analyzer's schema lattice so a Q5/Q7/
# Q8 pipeline's field references are checked at compile time
# (analysis/dataflow.py; the generators' output dicts must match).
BID_SCHEMA = {"auction": "int64", "bidder": "int64", "price": "float32"}
PERSON_SCHEMA = {"person": "int64", "state_id": "int64"}
AUCTION_SCHEMA = {"auction": "int64", "seller": "int64",
                  "category": "int64", "reserve": "float32"}


def _event_ids(cfg: NexmarkConfig, split: int, index: int) -> Tuple[np.ndarray, np.ndarray]:
    """Global event ids + event-time for one batch (monotone per split,
    interleaved across splits)."""
    b = cfg.batch_size
    base = (index * cfg.n_splits + split) * b
    ids = base + np.arange(b, dtype=np.int64)
    ts = ids // cfg.events_per_ms
    return ids, ts


def bid_stream(cfg: NexmarkConfig) -> GeneratorSource:
    """Bids only (Q5/Q7 input): fields auction, bidder, price. Hot
    auctions get 1/hot_ratio of the traffic (zipf-ish skew)."""

    def gen(split: str, i: int) -> Optional[Tuple[Dict[str, np.ndarray], np.ndarray]]:
        if i >= cfg.n_batches:
            return None
        ids, ts = _event_ids(cfg, int(split), i)
        b = cfg.batch_size
        n_hot = max(1, cfg.num_active_auctions // HOT_AUCTION_RATIO)
        # C fast path: on the single-core bench host the numpy RNG body
        # costs ~116ms per 2^20 batch (the log-normal price dominates) —
        # more than the whole rest of the pipeline. Same distributions,
        # different (still deterministic) stream.
        from flink_tpu.native_codec import nexmark_bids_native
        native = nexmark_bids_native(
            (int(split) << 20) | i, b, cfg.hot_ratio, n_hot,
            cfg.num_active_auctions, cfg.num_active_people)
        if native is not None:
            auction, bidder, price = native
            return ({"auction": auction, "bidder": bidder,
                     "price": price}, ts)
        rng = np.random.default_rng((int(split) << 20) | i)
        hot = rng.integers(0, cfg.hot_ratio, b) == 0
        auction = np.where(
            hot,
            rng.integers(0, n_hot, b),
            rng.integers(0, cfg.num_active_auctions, b),
        ).astype(np.int64)
        bidder = rng.integers(0, cfg.num_active_people, b).astype(np.int64)
        price = np.round(np.exp(rng.normal(6.0, 1.0, b)), 2).astype(np.float32)
        return ({"auction": auction, "bidder": bidder, "price": price}, ts)

    return GeneratorSource(gen, n_splits=cfg.n_splits,
                           schema=BID_SCHEMA)


@dataclasses.dataclass(frozen=True)
class _NexmarkDeviceBidGen:
    """jnp-traceable bid generator, bit-identical to codec.cc smx().
    A frozen dataclass (hash/eq by parameters) so it is a STABLE jit
    static argument: two sources with the same shape share the compiled
    devgen step across jobs — the warmup-shares-compilation contract.

    ``sub_batches`` > 1 re-slices the stream (pipeline.sub-batches):
    ``batch_size`` is then the SUB-batch size, index ``s`` yields the
    bit-exact slice [off, off + batch_size) of LOGICAL batch s //
    sub_batches (off = (s % sub_batches) * batch_size) — the splitmix
    counter is seeded from the logical index and advanced by the
    within-logical-batch record offset, so the record stream is
    IDENTICAL at every sub-batch count."""

    batch_size: int
    events_per_ms: int
    hot_ratio: int
    n_hot: int
    n_auctions: int
    sub_batches: int = 1

    def __call__(self, batch_index):
        import jax.numpy as jnp

        b = self.batch_size
        k = self.sub_batches
        logical = batch_index // k if k > 1 else batch_index
        # within-logical-batch record offset of this sub-batch
        off = (batch_index % k) * b if k > 1 else batch_index * 0
        # counter-based splitmix64, bit-identical to codec.cc smx()
        # (single split: the C seed for LOGICAL batch i is just i)
        G = jnp.uint64(0x9E3779B97F4A7C15)
        base = (logical.astype(jnp.uint64)
                * jnp.uint64(0xD1342543DE82EF95) + jnp.uint64(1))
        idx = off.astype(jnp.uint64) + jnp.arange(b, dtype=jnp.uint64)
        z = base + idx * G + G  # smx advances the counter BEFORE mixing
        z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        r1 = z ^ (z >> jnp.uint64(31))
        hot = ((r1 & jnp.uint64(0xFF))
               % jnp.uint64(self.hot_ratio)) == 0
        a32 = (r1 >> jnp.uint64(8)) & jnp.uint64(0xFFFFFFFF)
        auction = jnp.where(
            hot, (a32 * jnp.uint64(self.n_hot)) >> jnp.uint64(32),
            (a32 * jnp.uint64(self.n_auctions))
            >> jnp.uint64(32)).astype(jnp.int64)
        ids = (logical * (b * k) + off
               + jnp.arange(b, dtype=jnp.int64))
        ts = ids // self.events_per_ms
        return auction, ts


def bid_stream_device(cfg: NexmarkConfig,
                      sub_batches: int = 1) -> "DeviceGeneratorSource":
    """Device-resident bid generator (Q5/Q7 input): the same
    counter-based splitmix64 stream as ``native/codec.cc nexmark_bids``,
    expressed in jnp so the consuming operator's step program can
    synthesize the batch ON the accelerator (see
    ops/window.py devgen_step_kernel). ``device_keys_ts`` is BIT-EXACT
    with the C generator's auction lane — verified by
    tests/test_devgen.py — so the host can repair key-table misses and
    replay after restore from the identical stream.

    ``sub_batches`` > 1 presents the IDENTICAL record stream at
    ``cfg.batch_size / sub_batches`` granularity (the driver calls this
    through ``DeviceGeneratorSource.subdivided`` when
    ``pipeline.sub-batches`` is set): sub-batch index s covers the
    bit-exact slice of logical batch s // sub_batches, so committed
    output is byte-identical at every sub-batch count."""
    from flink_tpu.api.sources import DeviceGeneratorSource

    if cfg.n_splits != 1:
        # the device formula and ts_bounds assume the single-split id
        # base i*batch_size; _event_ids interleaves splits — mixing the
        # two would break the bit-exact miss-repair contract
        raise ValueError("bid_stream_device requires n_splits == 1")
    k = int(sub_batches)
    if k < 1 or cfg.batch_size % k:
        raise ValueError(
            f"sub_batches={k} must be >= 1 and divide "
            f"batch_size={cfg.batch_size}")
    host = bid_stream(cfg)
    B = cfg.batch_size          # LOGICAL batch size (the seed unit)
    b = B // k                  # produced (sub-)batch size
    n_hot = max(1, cfg.num_active_auctions // HOT_AUCTION_RATIO)
    device_keys_ts = _NexmarkDeviceBidGen(
        batch_size=b, events_per_ms=cfg.events_per_ms,
        hot_ratio=cfg.hot_ratio, n_hot=n_hot,
        n_auctions=cfg.num_active_auctions, sub_batches=k)

    # one-entry memo: a logical batch's K sub-repairs (or its K gen
    # fallbacks below) synthesize the C batch once, not K times
    _host_memo: list = [(-1, None)]

    def _host_logical(logical: int):
        from flink_tpu.native_codec import nexmark_bids_native

        if _host_memo[0][0] != logical:
            _host_memo[0] = (logical, nexmark_bids_native(
                logical, B, cfg.hot_ratio, n_hot,
                cfg.num_active_auctions, cfg.num_active_people))
        return _host_memo[0][1]

    def keys_ts_host(s: int):
        logical, off = s // k, (s % k) * b
        native = _host_logical(logical)
        ids = logical * B + off + np.arange(b, dtype=np.int64)
        return native[0][off:off + b], ids // cfg.events_per_ms

    def ts_bounds(s: int):
        base = (s // k) * B + (s % k) * b
        return base // cfg.events_per_ms, (base + b - 1) // cfg.events_per_ms

    _gen_memo: list = [(None, None)]

    def gen(split: str, s: int):
        # host-materialization fallback (a devgen gate closed): the C
        # generator's seed unit is the LOGICAL batch — synthesize it
        # once per logical index (memo) and slice this sub-batch out
        if k == 1:
            return host.gen(split, s)
        key = (split, s // k)
        if _gen_memo[0][0] != key:
            _gen_memo[0] = (key, host.gen(split, s // k))
        full = _gen_memo[0][1]
        if full is None:
            return None
        data, ts = full
        off = (s % k) * b
        return ({kk: v[off:off + b] for kk, v in data.items()},
                ts[off:off + b])

    return DeviceGeneratorSource(
        gen=gen, device_keys_ts=device_keys_ts,
        keys_ts_host=keys_ts_host, ts_bounds=ts_bounds,
        key_field="auction", batch_size=b, n_batches=cfg.n_batches * k,
        # multiply-shift range reduction: auction < n_auctions ALWAYS
        key_domain=cfg.num_active_auctions, keys_bounded=True,
        schema=BID_SCHEMA,
        # further subdivision re-derives from the config so the logical
        # seed unit stays cfg.batch_size (only the K=1 source carries
        # it; the driver subdivides exactly once)
        subdivide=(lambda kk: bid_stream_device(cfg, sub_batches=kk))
        if k == 1 else None)


def person_stream(cfg: NexmarkConfig) -> GeneratorSource:
    """New-person events (Q8 left input): fields person, state_id."""

    def gen(split: str, i: int) -> Optional[Tuple[Dict[str, np.ndarray], np.ndarray]]:
        if i >= cfg.n_batches:
            return None
        ids, ts = _event_ids(cfg, int(split), i)
        rng = np.random.default_rng(0x9E3779B9 ^ ((int(split) << 20) | i))
        b = cfg.batch_size
        person = (ids * PERSON_PROPORTION // TOTAL_PROPORTION) % (
            cfg.num_active_people) + rng.integers(0, 2, b)
        return ({"person": person.astype(np.int64),
                 "state_id": rng.integers(0, 50, b).astype(np.int64)}, ts)

    return GeneratorSource(gen, n_splits=cfg.n_splits,
                           schema=PERSON_SCHEMA)


def auction_stream(cfg: NexmarkConfig) -> GeneratorSource:
    """New-auction events (Q8 right input): fields auction, seller,
    category, reserve."""

    def gen(split: str, i: int) -> Optional[Tuple[Dict[str, np.ndarray], np.ndarray]]:
        if i >= cfg.n_batches:
            return None
        ids, ts = _event_ids(cfg, int(split), i)
        rng = np.random.default_rng(0x85EBCA6B ^ ((int(split) << 20) | i))
        b = cfg.batch_size
        seller = rng.integers(0, cfg.num_active_people, b).astype(np.int64)
        return ({
            "auction": ids,
            "seller": seller,
            "category": rng.integers(0, 5, b).astype(np.int64),
            "reserve": np.round(np.exp(rng.normal(6.0, 1.0, b)), 2).astype(np.float32),
        }, ts)

    return GeneratorSource(gen, n_splits=cfg.n_splits,
                           schema=AUCTION_SCHEMA)
