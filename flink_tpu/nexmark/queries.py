"""NEXMark query pipelines over the DataStream API.

ref: BASELINE.json configs — Q5 sliding hot items, Q7 tumbling highest
bid, Q8 tumbling new-user join; semantics per the nexmark/nexmark query
definitions (SQL in the external repo; validated shapes in SURVEY §7).
"""
from __future__ import annotations

from typing import Optional

from flink_tpu.api.datastream import DataStream
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import Sink
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.ops import aggregates
from flink_tpu.time.watermarks import WatermarkStrategy


def q5_hot_items(
    env: StreamExecutionEnvironment,
    bids,
    sink: Sink,
    *,
    window_ms: int = 10_000,
    slide_ms: int = 1_000,
    out_of_orderness_ms: int = 0,
) -> DataStream:
    """Q5: which auctions have the most bids per sliding window?

    Stage 1 (device): per-auction COUNT over the sliding window — the
    north-star hot path. Stage 2 (host, per fired batch): argmax per
    window over the per-auction counts; all fires of one window land in
    one batch (one watermark advance fires a window exactly once), so
    the per-batch group-by is exact.
    """
    stream = env.from_source(
        bids, WatermarkStrategy.for_bounded_out_of_orderness(out_of_orderness_ms))
    top = (
        stream.key_by("auction")
        .window(SlidingEventTimeWindows.of(window_ms, slide_ms))
        .count()
        # per-window argmax (ties kept) FUSED into the device fire path:
        # the full per-auction count tensor never leaves HBM; only each
        # window's hot items cross to the host
        .top(1, by="count")
    )

    def rename(data):
        return {"auction": data["key"], "window_end": data["window_end"],
                "bid_count": data["count"]}

    out = top.map(rename, name="q5_rename")
    out.add_sink(sink)
    return out


def q7_highest_bid(
    env: StreamExecutionEnvironment,
    bids,
    sink: Sink,
    *,
    window_ms: int = 10_000,
    out_of_orderness_ms: int = 0,
) -> DataStream:
    """Q7: highest bid per tumbling window — the windowAll/global reduce
    shape, WITHOUT the reference's parallelism-1 funnel: the global max
    folds per pane host-side (see ops/window_all.py for the measured
    bandwidth rationale), so no key shard or device is a hotspot."""
    stream = env.from_source(
        bids, WatermarkStrategy.for_bounded_out_of_orderness(out_of_orderness_ms))
    out = (
        stream.window_all(TumblingEventTimeWindows.of(window_ms))
        .max("price")
    )
    out.add_sink(sink)
    return out


def q8_monitor_new_users(
    env: StreamExecutionEnvironment,
    persons,
    auctions,
    sink: Sink,
    *,
    window_ms: int = 10_000,
    out_of_orderness_ms: int = 0,
) -> DataStream:
    """Q8: persons who created an auction in the same tumbling window
    they registered in (person ⋈ auction-on-seller)."""
    wm = WatermarkStrategy.for_bounded_out_of_orderness(out_of_orderness_ms)
    p = env.from_source(persons, wm)
    a = env.from_source(auctions, wm)
    out = (
        p.join(a).where("person").equal_to("seller")
        .window(TumblingEventTimeWindows.of(window_ms))
        .apply(left_fields=("state_id",), right_fields=("reserve",))
    )
    out.add_sink(sink)
    return out
