"""Storage fsck — offline integrity verification for the durable tiers.

``python -m flink_tpu fsck PATH [--repair] [--json]`` walks a log
TOPIC directory, a CHECKPOINT directory (a job dir of ``chk-*``
children, a single checkpoint dir, or a storage root of job dirs), or
an LSM STATE STORE directory (``MANIFEST.json`` with format
``lsm-state``, ``state/lsm.py``) — autodetected — and verifies what
the online readers assume:

- **segments**: every committed/compacted columnar file decodes whole —
  block CRCs (the ``native_codec.crc32`` path ``formats_columnar``
  verifies with), footer tripwire, row counts vs the commit marker's
  promise;
- **coherence**: committed offset ranges contiguous above the floor,
  compaction manifest generation sane (referenced files exist, cover
  the declared ranges), marker pairs (a pre without a commit is a
  staged transaction — suspicious in a quiesced topic), lease files
  parseable with un-expired deadlines;
- **coordination records** (PR 18): consumer-group membership
  manifests parse and generation-keyed offset commits never run AHEAD
  of their group's manifest generation (the fence admits only the
  current generation — an offset beyond it means manifest rollback or
  hand damage); the background cleaner's lease parses and is flagged
  when expired without release (crashed cleaner service — the next
  acquirer takes over at epoch+1);
- **orphans**: ``.tmp`` debris, segments no marker/manifest references,
  ``.inprogress`` checkpoint dirs, manifest-less final-name checkpoint
  dirs, objstore conditional-put serialization scratch (``*.lock~``
  on the raw backing directory — a crashed ``put_if`` leaves at most
  one; swept only under the maintenance lock and past the age grace);
- **lsm state stores**: every manifest-listed run file exists and
  decodes whole with the promised row count, the seq counter covers
  every run (a lower counter would re-mint a live run's name), run
  names unique; ``.tmp`` debris and unreferenced ``run-*.seg``
  (crashed seal/compact pre-swap output, or compaction-replaced files
  awaiting their grace sweep) report as repairable orphans.

``--repair`` applies ONLY the already-safe sweeps — exactly what the
online recovery paths (``TopicAppender.sweep_orphans``, checkpoint
``_retire_old``) would do: delete ``.tmp`` debris, unreferenced
segment/cmp files, ``.inprogress`` and manifest-less checkpoint dirs.
It never touches markers, leases, group offsets, or any file a marker
or manifest references: those repairs need the owning writer's context
(a deleted pre marker aborts someone's live transaction).

Exit contract (the analyze/lint CLI shape, asserted in tests/
test_cli.py): 0 = clean, 1 = findings, 2 = usage/path error.

Finding shape (one JSON object per line under ``--json``): ``rule``,
``severity`` (error|warn), ``path``, ``message``, ``repairable``,
and ``repaired`` after a ``--repair`` pass.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from flink_tpu.formats_columnar import ColumnarError, iter_blocks
from flink_tpu.fs import get_filesystem
from flink_tpu.log.topic import (
    GROUP_DIR,
    LEASE_DIR,
    OFFSET_COL,
    _CMP_RE,
    _SEG_RE,
    LogError,
    _list_markers,
    _partition_dir,
    _read_json,
    _txn_dir,
    load_manifest,
)

__all__ = ["fsck_path", "fsck_topic", "fsck_checkpoints", "main"]


# a repairable topic FILE younger than this is skipped by --repair:
# between a live producer's segment rename and its pre-commit marker
# the file is indistinguishable from debris (the stage-window grace)
REPAIR_MIN_AGE_S = 60.0


def _older_than(path: str, age_s: float) -> bool:
    from flink_tpu.log.topic import _local_path

    local = _local_path(path)
    if local is None:
        return True  # non-local: no mtime to consult — lease guard
        # and the maintenance lock remain the protections
    try:
        return (time.time() - os.path.getmtime(local)) > age_s
    except OSError:
        return False  # vanished/unstattable: do not touch it


def _f(rule: str, severity: str, path: str, message: str,
       repairable: bool = False) -> Dict[str, Any]:
    return {"rule": rule, "severity": severity, "path": path,
            "message": message, "repairable": repairable,
            "repaired": False}


def _classify_columnar(e: Exception) -> str:
    msg = str(e).lower()
    if "crc" in msg:
        return "SEGMENT_CRC"
    if "truncat" in msg or "footer" in msg or "empty columnar" in msg:
        return "SEGMENT_TRUNCATED"
    return "SEGMENT_CORRUPT"


def _verify_segment(fs, path: str, schema, promised_rows: Optional[int],
                    findings: List[Dict[str, Any]]) -> None:
    """Full decode pass: header + every block CRC + footer; row count
    vs the marker/manifest promise."""
    try:
        with fs.open_read(path) as f:
            data = f.read()
        if isinstance(data, str):
            data = data.encode("utf-8")
        rows = 0
        for block in iter_blocks(data, expect_schema=schema):
            rows += len(next(iter(block.values()), ()))
        if promised_rows is not None and rows != promised_rows:
            findings.append(_f(
                "SEGMENT_ROWS_MISMATCH", "error", path,
                f"segment holds {rows} rows, its marker promised "
                f"{promised_rows}"))
    except OSError as e:
        findings.append(_f("SEGMENT_MISSING", "error", path,
                           f"referenced segment unreadable: {e}"))
    except ColumnarError as e:
        findings.append(_f(_classify_columnar(e), "error", path,
                           f"segment fails verification: {e}"))


# -- topic --------------------------------------------------------------

def fsck_topic(path: str) -> List[Dict[str, Any]]:
    fs = get_filesystem(path)
    findings: List[Dict[str, Any]] = []
    try:
        meta = _read_json(fs, os.path.join(path, "meta.json"),
                          "topic meta")
        partitions = int(meta["partitions"])
    except (LogError, OSError, KeyError, ValueError) as e:
        return [_f("CORRUPT_CONTROL", "error",
                   os.path.join(path, "meta.json"),
                   f"unparseable topic meta: {e}")]

    # markers (loud parse -> finding, not a crash)
    try:
        pres = _list_markers(fs, path, "pre")
        commits = _list_markers(fs, path, "commit")
    except LogError as e:
        return findings + [_f("CORRUPT_CONTROL", "error",
                              _txn_dir(path),
                              f"unparseable transaction marker: {e}")]

    schema = None
    for key in sorted(commits):
        if commits[key].get("schema"):
            schema = tuple((str(n), str(t))
                           for n, t in commits[key]["schema"])
    sparse_schema = ((OFFSET_COL, "i64"),) + schema if schema else None

    # compaction manifest FIRST: it defines the per-partition floor
    # below which commit-marker segments are legitimately superseded
    try:
        manifest = load_manifest(fs, path)
    except LogError as e:
        manifest = None
        findings.append(_f("CORRUPT_CONTROL", "error",
                           os.path.join(path, "manifest.json"),
                           f"unparseable compaction manifest: {e}"))
    live_cmp: Dict[int, set] = {p: set() for p in range(partitions)}
    floor: Dict[int, int] = {p: 0 for p in range(partitions)}
    if manifest is not None:
        for p, entry in manifest.get("partitions", {}).items():
            p = int(p)
            floor[p] = max(int(entry.get("start", 0)),
                           int(entry.get("compacted_end", 0)))
            at = int(entry.get("start", 0))
            for s in entry.get("segments", []):
                live_cmp.setdefault(p, set()).add(s["name"])
                seg = os.path.join(_partition_dir(path, p), s["name"])
                if not fs.exists(seg):
                    findings.append(_f(
                        "MANIFEST_SEGMENT_MISSING", "error", seg,
                        f"manifest gen {manifest['gen']} references a "
                        "compacted segment that does not exist"))
                else:
                    _verify_segment(fs, seg, sparse_schema,
                                    int(s["rows"]), findings)
                if int(s["base"]) < at:
                    findings.append(_f(
                        "MANIFEST_INCOHERENT", "error", seg,
                        f"compacted segment covers [{s['base']}, "
                        f"{s['end']}) below the running floor {at}"))
                at = int(s["end"])

    # committed segments: existence + CRC/footer + row promise —
    # EXCEPT ranges wholly below the compaction/retention floor, whose
    # raw files were superseded by the manifest generation (a still-
    # present superseded file is droppable debris, reported as orphan
    # below, not verified as live data)
    referenced: Dict[int, set] = {p: set() for p in range(partitions)}
    for (cid, writer), marker in sorted(commits.items()):
        for p_s, segs in marker.get("segments", {}).items():
            p = int(p_s)
            for s in segs:
                end = int(s["base"]) + int(s["rows"])
                if end <= floor.get(p, 0):
                    continue  # superseded by the manifest generation:
                    # the raw file may legitimately be gone; if still
                    # present it reports as a repairable orphan below
                referenced.setdefault(p, set()).add(s["name"])
                _verify_segment(
                    fs,
                    os.path.join(_partition_dir(path, p), s["name"]),
                    schema, int(s["rows"]), findings)

    # staged (pre-without-commit) markers: orphan candidates
    for (cid, writer), marker in sorted(pres.items()):
        if (cid, writer) in commits:
            continue
        missing = []
        for p_s, segs in marker.get("segments", {}).items():
            for s in segs:
                referenced.setdefault(int(p_s), set()).add(s["name"])
                seg = os.path.join(_partition_dir(path, int(p_s)),
                                   s["name"])
                if not fs.exists(seg):
                    missing.append(s["name"])
        mpath = os.path.join(_txn_dir(path), f"pre-{cid:010d}"
                             + (f"-w.{writer}" if writer else "")
                             + ".json")
        findings.append(_f(
            "ORPHAN_PRE_MARKER", "warn", mpath,
            f"pre-commit marker cid={cid} writer={writer or '<single>'} "
            f"has no commit marker"
            + (f" and {len(missing)} of its staged segments are "
               f"missing ({missing[:3]}...)" if missing else
               " (staged transaction — live producer, or a crashed "
               "attempt recovery will roll back)")))

    # offset-chain coherence above the floor (the TopicReader contract)
    try:
        from flink_tpu.log.topic import TopicReader

        TopicReader(path)
    except (LogError, ColumnarError) as e:
        findings.append(_f("OFFSETS_BROKEN", "error", path,
                           f"committed offset chain is broken: {e}"))
    except OSError:
        pass  # per-segment findings above already name the files

    # orphans: tmp debris + unreferenced segment/cmp files
    for p in range(partitions):
        pdir = _partition_dir(path, p)
        if not fs.exists(pdir):
            continue
        for name in sorted(fs.listdir(pdir)):
            fpath = os.path.join(pdir, name)
            if name.endswith(".tmp"):
                findings.append(_f(
                    "ORPHAN_FILE", "warn", fpath,
                    "write-in-progress debris (crashed writer)",
                    repairable=True))
            elif _SEG_RE.match(name):
                if name not in referenced.get(p, set()):
                    findings.append(_f(
                        "ORPHAN_FILE", "warn", fpath,
                        "segment referenced by no pre/commit marker "
                        "(torn prepare or superseded by compaction)",
                        repairable=True))
            elif _CMP_RE.match(name):
                if name not in live_cmp.get(p, set()):
                    findings.append(_f(
                        "ORPHAN_FILE", "warn", fpath,
                        "compacted segment outside the current "
                        "manifest generation (crashed or superseded "
                        "pass)", repairable=True))

    # leases: parseable, not silently expired
    ldir = os.path.join(path, LEASE_DIR)
    if fs.exists(ldir):
        now = int(time.time() * 1000)
        for name in sorted(fs.listdir(ldir)):
            # the .json suffix also excludes "pN.json.lock" acquire locks
            if not name.endswith(".json"):
                continue
            lpath = os.path.join(ldir, name)
            try:
                rec = _read_json(fs, lpath, "lease file")
            except LogError as e:
                findings.append(_f("CORRUPT_CONTROL", "error", lpath,
                                   f"unparseable lease: {e}"))
                continue
            if (not rec.get("released")
                    and int(rec.get("deadline_ms", 0)) < now):
                findings.append(_f(
                    "STALE_LEASE", "warn", lpath,
                    f"lease held by {rec.get('owner')!r} (epoch "
                    f"{rec.get('epoch')}) expired at "
                    f"{rec.get('deadline_ms')} without release — "
                    "crashed producer; the next acquirer takes over "
                    "at epoch+1"))

    # cleaner service records: lease parseable and not silently
    # expired, published status parseable (a torn status would be a
    # PUT-atomicity violation — the cleaner publishes both via
    # CAS/atomic-rename)
    from flink_tpu.log.cleaner import CLEANER_LEASE, CLEANER_STATUS

    cl_path = os.path.join(path, CLEANER_LEASE)
    if fs.exists(cl_path):
        try:
            rec = _read_json(fs, cl_path, "cleaner lease")
        except LogError as e:
            findings.append(_f("CORRUPT_CONTROL", "error", cl_path,
                               f"unparseable cleaner lease: {e}"))
        else:
            now = int(time.time() * 1000)
            if (not rec.get("released")
                    and int(rec.get("deadline_ms", 0)) < now):
                findings.append(_f(
                    "STALE_CLEANER_LEASE", "warn", cl_path,
                    f"cleaner lease held by {rec.get('owner')!r} "
                    f"(epoch {rec.get('epoch')}) expired at "
                    f"{rec.get('deadline_ms')} without release — "
                    "crashed cleaner service; the next service takes "
                    "over at epoch+1 and its first verify() deposes "
                    "any zombie pass"))
    cs_path = os.path.join(path, CLEANER_STATUS)
    if fs.exists(cs_path):
        try:
            _read_json(fs, cs_path, "cleaner status")
        except LogError as e:
            findings.append(_f("CORRUPT_CONTROL", "error", cs_path,
                               f"unparseable cleaner status: {e}"))

    # consumer-group offsets: parseable, and generation-keyed commits
    # coherent with the group's membership manifest — an offset
    # recorded at a generation the manifest has never reached means
    # the fence was bypassed (or the manifest was rolled back by
    # hand), and the exactly-once handover accounting is suspect
    from flink_tpu.log.bus import ConsumerGroups

    gdir = os.path.join(path, GROUP_DIR)
    if fs.exists(gdir):
        for gname in sorted(fs.listdir(gdir)):
            sub = os.path.join(gdir, gname)
            if not fs.is_dir(sub):
                continue
            manifest_gen: Optional[int] = None
            mpath = os.path.join(sub, ConsumerGroups.MEMBERSHIP)
            if fs.exists(mpath):
                try:
                    mrec = _read_json(fs, mpath,
                                      "group membership manifest")
                    manifest_gen = int(mrec["generation"])
                    if not isinstance(mrec.get("members"), list):
                        raise KeyError("members")
                except (LogError, KeyError, ValueError, TypeError) as e:
                    findings.append(_f(
                        "CORRUPT_CONTROL", "error", mpath,
                        f"unparseable group membership manifest: {e}"))
            for name in sorted(fs.listdir(sub)):
                if (not name.endswith(".json")
                        or name == ConsumerGroups.MEMBERSHIP):
                    continue
                opath = os.path.join(sub, name)
                try:
                    rec = _read_json(fs, opath, "group-offset file")
                    int(rec["offset"])
                except (LogError, KeyError, ValueError, TypeError) as e:
                    findings.append(_f(
                        "CORRUPT_CONTROL", "error", opath,
                        f"unparseable group offset: {e}"))
                    continue
                if "generation" not in rec:
                    continue
                ogen = int(rec["generation"])
                if manifest_gen is None:
                    findings.append(_f(
                        "GROUP_GENERATION_INCOHERENT", "error", opath,
                        f"offset committed at generation {ogen} but "
                        f"group {gname!r} has no membership manifest "
                        "— a generation-keyed commit cannot pass the "
                        "fence without one"))
                elif ogen > manifest_gen:
                    findings.append(_f(
                        "GROUP_GENERATION_INCOHERENT", "error", opath,
                        f"offset committed at generation {ogen} ahead "
                        f"of the membership manifest's {manifest_gen} "
                        "— the fence admits only the current "
                        "generation, so the manifest regressed "
                        "(rolled back or hand-damaged)"))

    # objstore serialization-lock scratch: a crashed conditional put
    # leaves at most one `.lock~` beside the object it was publishing.
    # The fake's listdir hides them (server internals), so the scan
    # walks the raw backing directory; sweepable once the holder is
    # provably gone (maintenance lock + age grace, applied by repair)
    _scan_lock_debris(fs, path, findings)
    return findings


def _scan_lock_debris(fs, path: str,
                      findings: List[Dict[str, Any]]) -> None:
    from flink_tpu.log.topic import _local_path

    local = _local_path(path)
    if local is None:
        backing = getattr(fs, "_backing", None)
        real = getattr(fs, "_real", None)
        if backing is None or real is None:
            return  # remote scheme without a reachable backing dir
        local = real(backing(path))
    if not os.path.isdir(local):
        return
    for dirpath, _dirs, files in os.walk(local):
        for name in sorted(files):
            if name.endswith(".lock~"):
                findings.append(_f(
                    "OBJSTORE_LOCK_DEBRIS", "warn",
                    os.path.join(dirpath, name),
                    "conditional-put serialization scratch left by a "
                    "crashed put_if (server-emulation lock, not a "
                    "durability structure)", repairable=True))


# -- lsm state store ----------------------------------------------------

def fsck_lsm(path: str) -> List[Dict[str, Any]]:
    """Verify an lsm state store directory (state/lsm.py) against its
    manifest — the run files are immutable once published, so a full
    decode pass is exactly what a restoring store would read."""
    findings: List[Dict[str, Any]] = []
    fs = get_filesystem(path)
    mpath = os.path.join(path, "MANIFEST.json")
    try:
        man = _read_json(fs, mpath, "lsm-state manifest")
    except (LogError, OSError) as e:
        return [_f("CORRUPT_CONTROL", "error", mpath,
                   f"unparseable lsm-state manifest: {e}")]
    runs = man.get("runs", [])
    seq = int(man.get("seq", 0))
    seen: set = set()
    for meta in runs:
        name = meta.get("name", "?")
        rpath = os.path.join(path, name)
        if name in seen:
            findings.append(_f(
                "LSM_MANIFEST_INCOHERENT", "error", rpath,
                f"run {name!r} listed twice in the manifest"))
        seen.add(name)
        if int(meta.get("seq", 0)) > seq:
            findings.append(_f(
                "LSM_MANIFEST_INCOHERENT", "error", rpath,
                f"run seq {meta.get('seq')} exceeds the manifest seq "
                f"counter {seq} — a restarting store would re-mint "
                "this live run's name"))
        if not fs.exists(rpath):
            findings.append(_f(
                "LSM_RUN_MISSING", "error", rpath,
                f"manifest gen {man.get('gen')} references a run that "
                "does not exist — the published state is unreadable"))
        else:
            # schema rides the run file itself (run_schema widths are
            # the aggregate's business, not the manifest's)
            _verify_segment(fs, rpath, None,
                            int(meta["rows"]) if "rows" in meta else None,
                            findings)
    for name in sorted(fs.listdir(path)):
        fpath = os.path.join(path, name)
        if name.endswith(".tmp"):
            findings.append(_f(
                "ORPHAN_FILE", "warn", fpath,
                "write-in-progress debris (crashed seal/compact)",
                repairable=True))
        elif (name.startswith("run-") and name.endswith(".seg")
              and name not in seen):
            findings.append(_f(
                "ORPHAN_FILE", "warn", fpath,
                "run referenced by no manifest generation (crashed "
                "pre-swap output, or compaction-replaced and awaiting "
                "the grace sweep)", repairable=True))
    return findings


# -- checkpoints --------------------------------------------------------

def _fsck_one_checkpoint(fs, d: str,
                         findings: List[Dict[str, Any]]) -> None:
    from flink_tpu.checkpoint import blobformat

    mf = os.path.join(d, "MANIFEST.json")
    if not fs.exists(mf):
        findings.append(_f(
            "CHECKPOINT_MANIFEST_MISSING", "error", d,
            "final-name checkpoint dir without MANIFEST.json — "
            "invisible to restore (a power cut between content and "
            "manifest can not produce this under manifest-last; "
            "likely a partially deleted or hand-damaged checkpoint)",
            repairable=True))
        return
    try:
        manifest = _read_json(fs, mf, "checkpoint manifest")
    except LogError as e:
        findings.append(_f("CORRUPT_CONTROL", "error", mf,
                           f"unparseable checkpoint manifest: {e}"))
        return

    def _check_blob(fpath: str) -> None:
        try:
            with fs.open_read(fpath) as f:
                raw = f.read()
        except OSError as e:
            findings.append(_f("CHECKPOINT_BLOB_MISSING", "error",
                               fpath, f"manifest references a missing "
                               f"blob: {e}"))
            return
        if isinstance(raw, str):
            raw = raw.encode()
        comp = manifest.get("compression", "none")
        if comp == "zlib":
            import zlib

            try:
                raw = zlib.decompress(raw)
            except zlib.error as e:
                findings.append(_f("CHECKPOINT_BLOB_CORRUPT", "error",
                                   fpath, f"undecompressable blob: {e}"))
                return
        if blobformat.is_v3(raw):
            try:
                blobformat.decode(raw)
            except Exception as e:  # noqa: BLE001 — any decode death
                findings.append(_f(
                    "CHECKPOINT_BLOB_CORRUPT", "error", fpath,
                    f"blob fails decode: {type(e).__name__}: {e}"))
        elif not raw:
            findings.append(_f("CHECKPOINT_BLOB_CORRUPT", "error",
                               fpath, "zero-byte blob"))

    fmt = int(manifest.get("format_version", 1))
    if fmt == 1 or manifest.get("layout") == "single":
        name = "state.blob" if fmt >= 3 else "state.pkl"
        _check_blob(os.path.join(d, name))
    else:
        _check_blob(os.path.join(
            d, "meta.blob" if fmt >= 3 else "meta.pkl"))
        for nid, entry in manifest.get("ops", {}).items():
            _check_blob(os.path.join(d, entry["file"]))


def fsck_checkpoints(path: str) -> List[Dict[str, Any]]:
    """``path`` is a job dir (chk-* children), one checkpoint dir, or
    a storage root (job dirs of chk-* children)."""
    fs = get_filesystem(path)
    findings: List[Dict[str, Any]] = []

    def _walk_job_dir(jdir: str) -> None:
        for name in sorted(fs.listdir(jdir)):
            d = os.path.join(jdir, name)
            if ".inprogress." in name:
                findings.append(_f(
                    "CHECKPOINT_INPROGRESS_ORPHAN", "warn", d,
                    "abandoned in-progress checkpoint dir (crashed or "
                    "fenced writer)", repairable=True))
            elif name.endswith(".tmp"):
                findings.append(_f("ORPHAN_FILE", "warn", d,
                                   "write-in-progress debris",
                                   repairable=True))
            elif (name.startswith("chk-")
                  or name.startswith("savepoint-")) and fs.is_dir(d):
                _fsck_one_checkpoint(fs, d, findings)

    base = os.path.basename(os.path.normpath(path))
    if base.startswith("chk-") or base.startswith("savepoint-"):
        _fsck_one_checkpoint(fs, path, findings)
        return findings
    names = fs.listdir(path)
    if any(n.startswith(("chk-", "savepoint-")) or ".inprogress." in n
           for n in names):
        _walk_job_dir(path)
        return findings
    # storage root: every child holding chk-* dirs is a job dir
    for name in sorted(names):
        jdir = os.path.join(path, name)
        if fs.is_dir(jdir) and any(
                n.startswith(("chk-", "savepoint-"))
                or ".inprogress." in n for n in fs.listdir(jdir)):
            _walk_job_dir(jdir)
    return findings


# -- entry points -------------------------------------------------------

def detect_kind(path: str) -> Optional[str]:
    """'topic' | 'checkpoint' | 'lsm' | None (unrecognizable)."""
    fs = get_filesystem(path)
    if not fs.exists(path) or not fs.is_dir(path):
        return None
    if fs.exists(os.path.join(path, "meta.json")):
        return "topic"
    mpath = os.path.join(path, "MANIFEST.json")
    if fs.exists(mpath):
        try:
            if _read_json(fs, mpath, "manifest").get(
                    "format") == "lsm-state":
                return "lsm"
        except (LogError, OSError):
            # damaged manifest: run files identify the tier anyway so
            # the lsm scan can REPORT the corruption instead of the
            # path reading as unrecognizable
            if any(n.startswith("run-") and n.endswith(".seg")
                   for n in fs.listdir(path)):
                return "lsm"
    base = os.path.basename(os.path.normpath(path))
    if base.startswith(("chk-", "savepoint-")):
        return "checkpoint"
    names = fs.listdir(path)
    if any(n.startswith(("chk-", "savepoint-")) or ".inprogress." in n
           for n in names):
        return "checkpoint"
    for name in names:
        sub = os.path.join(path, name)
        try:
            if fs.is_dir(sub) and any(
                    n.startswith(("chk-", "savepoint-"))
                    or ".inprogress." in n for n in fs.listdir(sub)):
                return "checkpoint"
        except OSError:
            continue
    return None


def fsck_path(path: str, repair: bool = False) -> List[Dict[str, Any]]:
    """Run the appropriate scan; with ``repair``, apply the safe sweeps
    (delete repairable orphans) and mark them ``repaired``. Raises
    ValueError for an unrecognizable path (the CLI's exit-2 leg)."""
    kind = detect_kind(path)
    if kind is None:
        raise ValueError(
            f"{path!r} is neither a log topic (no meta.json), a "
            "checkpoint directory (no chk-*/savepoint-* children), "
            "nor an lsm state store (no lsm-state MANIFEST.json)")
    findings = (fsck_topic(path) if kind == "topic"
                else fsck_lsm(path) if kind == "lsm"
                else fsck_checkpoints(path))
    if repair:
        fs = get_filesystem(path)
        # topic/lsm repairs run under the maintenance lock: an
        # unreferenced cmp/run file may be a LIVE pass's pre-swap output
        maint_fd = None
        live_leased: set = set()
        if kind == "topic":
            from flink_tpu.log.topic import (
                list_leases, release_maintenance_lock,
                try_maintenance_lock)

            maint_fd = try_maintenance_lock(path)
            now = int(time.time() * 1000)
            live_leased = {
                p for p, rec in list_leases(path).items()
                if not rec.get("released")
                and int(rec.get("deadline_ms", 0)) >= now}
        elif kind == "lsm":
            from flink_tpu.log.topic import try_maintenance_lock

            maint_fd = try_maintenance_lock(path)
            if maint_fd is None:
                return findings  # live seal/compact: nothing is safe
        try:
            for f in findings:
                if not f["repairable"]:
                    continue
                base = os.path.basename(f["path"])
                if f["rule"] == "OBJSTORE_LOCK_DEBRIS":
                    # raw backing-path debris: a live put_if may hold
                    # the lock this instant — sweep only under the
                    # maintenance lock and past the age grace, and
                    # unlink directly (the path is beneath the scheme,
                    # so the topic's fs must not re-map it)
                    if maint_fd is None:
                        continue
                    if not _older_than(f["path"], REPAIR_MIN_AGE_S):
                        continue
                    try:
                        os.unlink(f["path"])
                        f["repaired"] = True
                    except OSError:
                        pass
                    continue
                if kind == "topic":
                    # LIVE-PRODUCER guards: fsck has no writer identity
                    # (sweep_orphans restricts itself to OWNED
                    # partitions for the same window), so an offline
                    # sweep must not race a live stage — between a
                    # segment's rename and its pre marker the file
                    # looks orphaned, and a .tmp may be mid-write.
                    # Skip (a) any partition under a LIVE lease,
                    # (b) files younger than the stage-window grace,
                    # (c) cmp files when the maintenance lock is busy.
                    if maint_fd is None and base.startswith("cmp-"):
                        continue
                    pdir = os.path.basename(os.path.dirname(f["path"]))
                    if (pdir.startswith("p")
                            and pdir[1:].isdigit()
                            and int(pdir[1:]) in live_leased):
                        continue
                    if not _older_than(f["path"], REPAIR_MIN_AGE_S):
                        continue
                elif kind == "lsm":
                    # seal does not hold the maintenance lock — the
                    # age grace is what protects a live store's
                    # rename-pending tmp and pre-manifest run
                    if not _older_than(f["path"], REPAIR_MIN_AGE_S):
                        continue
                try:
                    fs.delete(f["path"], recursive=fs.is_dir(f["path"]))
                    f["repaired"] = True
                except OSError:
                    pass  # report stays repairable-but-unrepaired
        finally:
            if kind in ("topic", "lsm") and maint_fd is not None:
                from flink_tpu.log.topic import release_maintenance_lock

                release_maintenance_lock(path, maint_fd)
    return findings


def render(findings: List[Dict[str, Any]]) -> str:
    if not findings:
        return "fsck: clean (no findings)"
    lines = []
    for f in findings:
        tag = " [repaired]" if f["repaired"] else (
            " [repairable]" if f["repairable"] else "")
        lines.append(f"{f['severity'].upper():5s} {f['rule']}{tag} "
                     f"{f['path']}: {f['message']}")
    return "\n".join(lines)


def main(args) -> int:
    """CLI half (wired from flink_tpu/cli.py): 0 clean / 1 findings /
    2 usage-or-path error."""
    import sys

    try:
        findings = fsck_path(args.path, repair=args.repair)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        for f in findings:
            print(json.dumps(f))
    else:
        print(render(findings))
    # after a repair pass, fully-repaired findings no longer count
    open_findings = [f for f in findings if not f["repaired"]]
    return 1 if open_findings else 0
