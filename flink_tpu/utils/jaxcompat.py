"""jax API compatibility — one import site for symbols that moved
between jax releases.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to
``jax.shard_map``; containers pin either side of the move. Every
shard_map consumer (ops/window.py, exchange parity tests, bench_micro)
imports it from here, and the tier-1 capability probe in
tests/conftest.py keys on :data:`HAS_SHARD_MAP` — mesh tests skip
instead of erroring when NEITHER spelling exists.
"""
from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # pragma: no cover - no shard_map at all
        shard_map = None

HAS_SHARD_MAP = shard_map is not None

try:
    from jax.experimental.mesh_utils import create_hybrid_device_mesh
except ImportError:  # pragma: no cover - older mesh_utils layout
    create_hybrid_device_mesh = None

HAS_HYBRID_MESH = create_hybrid_device_mesh is not None


def hybrid_device_mesh(mesh_shape, dcn_mesh_shape, devices):
    """``create_hybrid_device_mesh`` with a reshape fallback: the ICI
    axes (``mesh_shape``) index within a slice, the DCN axes
    (``dcn_mesh_shape``) across slices (SNIPPETS.md [1] — the hybrid
    topology that keeps intra-slice collectives off the slow plane).
    Returns a device ndarray of elementwise shape ``dcn * ici``.

    The jax helper groups devices by process granule; on a
    single-granule fleet (one process's local devices, or the virtual
    CPU mesh) it rejects multi-slice shapes, so any single-granule —
    or shim-less — call falls back to a plain C-order reshape, which
    is exactly the hybrid layout when the device list is already
    slice-major."""
    import numpy as np

    devices = list(devices)
    shape = tuple(d * i for d, i in zip(dcn_mesh_shape, mesh_shape))
    if create_hybrid_device_mesh is not None and any(
            d > 1 for d in dcn_mesh_shape):
        granules = {getattr(d, "process_index", 0) for d in devices}
        if len(granules) > 1:
            return create_hybrid_device_mesh(
                mesh_shape, dcn_mesh_shape, devices)
    return np.asarray(devices, dtype=object).reshape(shape)
