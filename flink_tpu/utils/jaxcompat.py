"""jax API compatibility — one import site for symbols that moved
between jax releases.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to
``jax.shard_map``; containers pin either side of the move. Every
shard_map consumer (ops/window.py, exchange parity tests, bench_micro)
imports it from here, and the tier-1 capability probe in
tests/conftest.py keys on :data:`HAS_SHARD_MAP` — mesh tests skip
instead of erroring when NEITHER spelling exists.
"""
from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # pragma: no cover - no shard_map at all
        shard_map = None

HAS_SHARD_MAP = shard_map is not None
