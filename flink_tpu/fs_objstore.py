"""Fake conditional-put object store (``objstore://``).

The in-tree stand-in for S3/GCS-class storage: whole-object PUTs that
are durable when they return, list-after-write consistency, a per-
object ETag, and — the part the lock tiers care about — an atomic
``put_if(path, data, expected_etag)`` compare-and-swap (the
If-Match/x-goog-if-generation-match conditional write, HTTP 412 on
mismatch surfaced as :class:`~flink_tpu.fs.CASConflictError`). Every
O_EXCL + rename-first lock in the stack (writer leases, the HA leader
lease, the per-topic maintenance lock, consumer-group offsets,
manifest swaps) ports onto this primitive when the configured scheme
advertises ``conditional_put``; the local-fs path is unchanged.

Layout: ``objstore://<abs-path>`` stores the object at ``<abs-path>``
on a BACKING filesystem resolved through the ordinary registry, so the
store composes with CrashFS — ``install(inner_prefix="crash://")``
routes every mutation through the power-cut journal and the crash
explorer samples POSIX-legal images of the CAS paths. The ETag is the
content MD5 (exactly S3's simple-PUT ETag), so there is no sidecar
metadata to tear: any readable object has a well-defined generation.

Server-side atomicity: a real store serializes conditional writes in
the service; this fake emulates that with a short-lived local lock
file (``*.lock~``, never visible through ``listdir``) around the
read-compare-publish sequence. The lock is emulation scratch, not a
durability structure — a crashed process leaves at most one, swept by
fsck as objstore journal debris.

Honest residuals (documented in COMPONENTS.md row 86): this is a fake
— no real S3/GCS client, no network, no multi-host consistency beyond
the shared backing filesystem; ``rename`` stays atomic (a real object
store would copy+delete).

Fault point: ``fs.cas.put`` fires inside ``put_if`` (inject
``raise`` to synthesize 412 contention mid-takeover).
"""
from __future__ import annotations

import hashlib
import io
import os
import threading
import time
from typing import List, Optional

from flink_tpu.fs import (
    CASConflictError,
    FileSystem,
    get_filesystem,
    register_filesystem,
    write_atomic,
)

SCHEME = "objstore"
_LOCK_SUFFIX = ".lock~"
_LOCK_STALE_S = 5.0


class ObjectStoreFileSystem(FileSystem):
    """``objstore://`` — conditional-put object semantics over a
    backing filesystem (local by default, CrashFS under the
    crash-state explorer)."""

    conditional_put = True

    def __init__(self, inner_prefix: str = "") -> None:
        self._prefix = inner_prefix
        self._mu = threading.Lock()

    # -- path plumbing ----------------------------------------------------

    def _backing(self, path: str) -> str:
        _, sep, rest = path.partition("://")
        return self._prefix + (rest if sep else path)

    def _inner(self, path: str):
        return get_filesystem(self._backing(path))

    @staticmethod
    def _real(backing: str) -> str:
        # local scratch-lock location: the path component under any
        # scheme prefix (crash:// backing journals objects, but the
        # serialization lock is server emulation and stays raw-local)
        _, sep, rest = backing.partition("://")
        return rest if sep else backing

    # -- plain delegation (mapped onto the backing filesystem) ------------

    def open_read(self, path: str):
        return self._inner(path).open_read(self._backing(path))

    def open_write(self, path: str, sync: bool = False):
        # PUT semantics: buffer whole, publish at close — and a PUT
        # that returned IS durable, so the backing write always syncs
        return _BufferedPut(self._inner(path), self._backing(path))

    def fsync(self, path: str) -> None:
        self._inner(path).fsync(self._backing(path))

    def mkdirs(self, path: str) -> None:
        self._inner(path).mkdirs(self._backing(path))

    def exists(self, path: str) -> bool:
        return self._inner(path).exists(self._backing(path))

    def listdir(self, path: str) -> List[str]:
        # list-after-write consistent; serialization-lock scratch is
        # server internals, never a listed object
        return [n for n in self._inner(path).listdir(self._backing(path))
                if not n.endswith(_LOCK_SUFFIX)]

    def delete(self, path: str, recursive: bool = False) -> None:
        self._inner(path).delete(self._backing(path), recursive=recursive)

    def rename(self, src: str, dst: str) -> None:
        # fake simplification: delegated (atomic on the local backing).
        # A real object store renames by copy+delete — documented as an
        # honest residual, not relied on by the CAS lock tiers.
        self._inner(src).rename(self._backing(src), self._backing(dst))

    def link_or_copy(self, src: str, dst: str) -> None:
        self._inner(src).link_or_copy(self._backing(src),
                                      self._backing(dst))

    def size(self, path: str) -> int:
        return self._inner(path).size(self._backing(path))

    def is_dir(self, path: str) -> bool:
        return self._inner(path).is_dir(self._backing(path))

    # -- the conditional-write extension ----------------------------------

    def etag(self, path: str) -> Optional[str]:
        inner, backing = self._inner(path), self._backing(path)
        if not inner.exists(backing) or inner.is_dir(backing):
            return None
        with inner.open_read(backing) as f:
            return hashlib.md5(f.read()).hexdigest()

    def put_if(self, path: str, data: bytes,
               expected_etag: Optional[str] = None) -> str:
        from flink_tpu import faults

        faults.fire("fs.cas.put", exc=CASConflictError, path=path)
        backing = self._backing(path)
        with self._mu, _server_lock(self._real(backing)):
            current = self.etag(path)
            if current != expected_etag:
                raise CASConflictError(
                    f"conditional put of {path}: expected etag "
                    f"{expected_etag!r}, current {current!r}")
            write_atomic(self._inner(path), backing, bytes(data))
            return hashlib.md5(bytes(data)).hexdigest()


class _BufferedPut:
    """Whole-object PUT handle: bytes accumulate in memory and publish
    atomically (tmp + fsync + rename on the backing fs) when close()
    returns — no reader ever observes a torn object."""

    def __init__(self, inner, backing: str) -> None:
        self._inner = inner
        self._backing = backing
        self._buf = io.BytesIO()
        self._closed = False

    def write(self, data) -> int:
        return self._buf.write(bytes(data))

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        write_atomic(self._inner, self._backing, self._buf.getvalue())

    def __enter__(self) -> "_BufferedPut":
        return self

    def __exit__(self, *exc) -> None:
        if exc and exc[0] is not None:
            self._closed = True  # failed PUT publishes nothing
        else:
            self.close()


class _server_lock:
    """O_EXCL scratch lock emulating the store's server-side CAS
    serialization (cross-process — the CLI smoke chains jobs in
    separate processes). Stale locks from a crashed put_if break after
    a short grace; the file never outlives the operation on the happy
    path."""

    def __init__(self, real_path: str) -> None:
        self._path = real_path + _LOCK_SUFFIX

    def __enter__(self) -> None:
        parent = os.path.dirname(self._path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        deadline = time.monotonic() + _LOCK_STALE_S * 2
        while True:
            try:
                os.close(os.open(self._path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY))
                return
            except FileExistsError:
                try:
                    if (time.monotonic() - os.path.getmtime(self._path)
                            > _LOCK_STALE_S):
                        os.unlink(self._path)
                        continue
                except OSError:
                    continue  # holder released between stat and unlink
                if time.monotonic() > deadline:
                    raise CASConflictError(
                        f"objstore serialization lock stuck: {self._path}")
                time.sleep(0.005)

    def __exit__(self, *exc) -> None:
        try:
            os.unlink(self._path)
        except OSError:
            pass


def install(inner_prefix: str = "") -> ObjectStoreFileSystem:
    """Register ``objstore://`` over the given backing prefix and
    return the instance. ``install(inner_prefix="crash://")`` after
    ``fs_crash.install(root)`` journals every object mutation for the
    power-cut explorer."""
    fs = ObjectStoreFileSystem(inner_prefix)
    register_filesystem(SCHEME, lambda: fs)
    return fs


def register(registry) -> None:
    """plugins.modules hook (ref: FileSystemFactory SPI)."""
    registry.register(SCHEME, ObjectStoreFileSystem)
