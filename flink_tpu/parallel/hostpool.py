"""Shared host worker-pool plane — the multi-core host operator runtime.

The three host-resident operator paths (session span registry, windowAll
pane fold, host spill store) all serialized on one core (PROFILE.md §9,
VERDICT r05 weak #7 / missing #8). This module is the shared plane they
scale on: ONE ``HostPool`` per driver, sized by ``host.parallelism``,
handed to every operator that owns host-parallel work. The heavy passes
are numpy-dominated and release the GIL inside C loops, so a thread
pool (no pickling, shared address space) is the right executor shape.

Determinism contract (the §9.4 measurement/correctness gate):

- ``host.parallelism = 1`` is the EXACT serial path: no executor is
  created, tasks run inline on the caller thread in submission order —
  the single-core numbers in PROFILE.md stay reproducible.
- At any parallelism, ``run_tasks`` returns results in SUBMISSION
  order, so callers combine partials in a schedule-independent order.
  Every client combine is associative and exact on its lane monoids
  (max/min/count always; sums whenever the lane values are exactly
  representable, e.g. integer-valued f32 below 2**24 — the golden
  configs), so parallel results are byte-identical to serial. The one
  place the reduction TREE changes shape is the spill store's chunked
  tree fold, and it is gated on a batch-size floor
  (``host.fold-chunk-records``) with a chunk size that does not depend
  on the worker count.

Fault seam: every task submission passes the registered
``host.pool.task`` fault point (on the CALLER thread, before dispatch,
so per-point invocation indices follow deterministic submission order,
not worker interleaving). The chaos suite drives the sessions and
spill-overflow pipelines through recovery with this point armed at
``host.parallelism = 4``.

Observability: per-task metrics under the ``hostpool`` group —
``tasks_total``, ``task_ms`` (per-task wall), ``parallelism``.

Shared-state discipline (LINTED — ``HOSTPOOL_SHARED_WRITE`` in
analysis/pylints.py walks every ``run_tasks`` call site): a submitted
closure runs on a pool worker thread, so it must either

- **return a partial** and let the caller combine (results come back
  in submission order — the merge discipline every client here uses), or
- **guard shared writes with a lock** — the lint recognizes a
  ``with <...lock...>:`` block by name (the spill store's per-pane
  locks, metrics' ``_lock``), so name your locks ``*lock*``.

An unguarded ``self.total += n`` / ``shared[k] = v`` inside a task
closure is the read-modify-write race PR 5 fixed by hand in
obs/metrics.py — the lint keeps it from coming back.
"""
from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

from flink_tpu import faults
from flink_tpu.config import HostOptions

__all__ = ["HostPool"]

# the task-submit fault seam; registered in faults.KNOWN_FAULT_POINTS
TASK_FAULT_POINT = "host.pool.task"


class HostPool:
    """Lifecycle-managed shared worker pool for host-resident operator
    work. One per driver; operators receive it at construction and
    submit batches of independent thunks through ``run_tasks``."""

    def __init__(self, parallelism: int,
                 *, registry: Optional[Any] = None) -> None:
        parallelism = int(parallelism)
        if parallelism < 1:
            raise ValueError(
                f"host.parallelism must be >= 1 (1 = serial path), "
                f"got {parallelism}")
        self.parallelism = parallelism
        # parallelism 1 NEVER creates an executor: the serial path must
        # be exactly the pre-pool code path, thread-free
        self._executor: Optional[ThreadPoolExecutor] = (
            None if parallelism == 1 else ThreadPoolExecutor(
                max_workers=parallelism, thread_name_prefix="hostpool"))
        self._closed = False
        self._tasks = None
        self._task_ms = None
        if registry is not None:
            g = registry.group("hostpool")
            self._tasks = g.counter("tasks_total")
            self._task_ms = g.histogram("task_ms")
            g.gauge("parallelism").set(float(parallelism))

    @classmethod
    def from_config(cls, config, *, registry: Optional[Any] = None
                    ) -> "HostPool":
        """Size from ``host.parallelism`` (declared default:
        ``min(4, os.cpu_count())``). Values < 1 fail loudly here; the
        plan analyzer (HOST_PARALLELISM_INVALID) flags them — and
        oversubscription past ``os.cpu_count()`` — at submit."""
        return cls(int(config.get(HostOptions.PARALLELISM)),
                   registry=registry)

    # -- execution -------------------------------------------------------

    def _timed(self, fn: Callable[[], Any]) -> Any:
        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            if self._task_ms is not None:
                self._task_ms.update((time.perf_counter() - t0) * 1e3)

    def run_tasks(self, fns: Sequence[Callable[[], Any]]) -> List[Any]:
        """Run independent thunks; results in SUBMISSION order (the
        determinism contract's combine order). A task exception
        re-raises the first one by submission index. After ``close``
        (or at parallelism 1) tasks run inline on the caller thread."""
        if not fns:
            return []
        if self._executor is None or self._closed:
            out = []
            for fn in fns:
                faults.fire(TASK_FAULT_POINT)
                if self._tasks is not None:
                    self._tasks.inc()
                out.append(self._timed(fn))
            return out
        futures = []
        try:
            for fn in fns:
                # the fault seam sits at SUBMIT, on the caller thread:
                # injection schedules follow deterministic submission
                # order
                faults.fire(TASK_FAULT_POINT)
                if self._tasks is not None:
                    self._tasks.inc()
                futures.append(self._executor.submit(self._timed, fn))
        except BaseException:
            # a fault at the submit seam must drain what was already
            # dispatched before the error escapes — same no-orphan
            # guarantee as the result loop below: no worker may still
            # be mutating operator state when the caller's recovery
            # path resumes
            for f in futures:
                try:
                    f.result()
                except BaseException:
                    pass
            raise
        out: List[Any] = []
        first_err: Optional[BaseException] = None
        for f in futures:
            try:
                out.append(f.result())
            except BaseException as e:  # keep draining: no orphan task
                # may still be mutating operator state when the caller
                # resumes (recovery re-builds operators, but THIS
                # attempt's teardown must not race its own workers)
                if first_err is None:
                    first_err = e
                out.append(None)
        if first_err is not None:
            raise first_err
        return out

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Shut the executor down without waiting (a wedged task must
        not turn job teardown into a hang); later ``run_tasks`` calls
        degrade to the inline serial path."""
        self._closed = True
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    def __repr__(self) -> str:  # pragma: no cover
        return f"HostPool(parallelism={self.parallelism})"


def default_parallelism() -> int:
    """The declared default: ``min(4, os.cpu_count())`` (PROFILE §9.4)."""
    return min(4, os.cpu_count() or 1)
