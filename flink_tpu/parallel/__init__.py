from flink_tpu.parallel.mesh import MeshPlan, make_mesh_plan, AXIS

__all__ = ["MeshPlan", "make_mesh_plan", "AXIS"]
