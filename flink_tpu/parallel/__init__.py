from flink_tpu.parallel.hostpool import HostPool
from flink_tpu.parallel.mesh import MeshPlan, make_mesh_plan, AXIS

__all__ = ["HostPool", "MeshPlan", "make_mesh_plan", "AXIS"]
