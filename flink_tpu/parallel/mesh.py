"""Device mesh planning — the slot/TaskManager analogue.

The reference assigns each operator subtask a key-group range inside a
TaskManager slot (ref: runtime/taskexecutor/slot/TaskSlotTableImpl.java,
runtime/state/KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex).
Here a "subtask" is a TPU device in a 1-D ``jax.sharding.Mesh``; each
device owns a contiguous range of key shards, and keyed exchanges are XLA
collectives over the mesh axis (ICI within a slice, DCN across slices —
the sharding is the same, XLA picks the transport).

The mesh axis is named ``"d"`` throughout (data/devices); scaling to
multi-host is the same mesh built from ``jax.devices()`` across processes.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "d"


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Static plan binding key shards to mesh devices.

    num_shards plays maxParallelism (fixed hash space, default 128);
    each device owns ``shards_per_device`` contiguous shards, i.e. the
    key-group range of that "subtask".
    """

    mesh: Mesh
    num_shards: int
    slots_per_shard: int

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def shards_per_device(self) -> int:
        return self.num_shards // self.n_devices

    @property
    def slots_per_device(self) -> int:
        return self.shards_per_device * self.slots_per_shard

    @property
    def total_slots(self) -> int:
        return self.num_shards * self.slots_per_shard

    @property
    def rows_per_device(self) -> int:
        return self.slots_per_device + 1  # + per-device dump row

    def shard_range(self, device_index: int) -> Tuple[int, int]:
        s = self.shards_per_device
        return (device_index * s, (device_index + 1) * s)

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def row_sharding(self) -> NamedSharding:
        """Sharding for state arrays: leading (device-blocked rows) axis."""
        return self.sharding(AXIS)

    def batch_sharding(self) -> NamedSharding:
        """Sharding for record batches: leading batch axis split across
        devices (arrival distribution, pre-keyBy)."""
        return self.sharding(AXIS)

    def device_of_slot(self, global_slots: np.ndarray) -> np.ndarray:
        return global_slots // self.slots_per_device

    def global_slot_to_row(self, global_slots: np.ndarray) -> np.ndarray:
        """Global slot id → row index in the (n_dev * rows_per_device)
        state array (each device block carries one extra dump row)."""
        dev = global_slots // self.slots_per_device
        return global_slots + dev  # + one dump row per preceding device


def make_mesh_plan(
    num_shards: int,
    slots_per_shard: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> MeshPlan:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if num_shards % n != 0:
        raise ValueError(
            f"state.num-key-shards ({num_shards}) must be a multiple of the "
            f"device count ({n}) — the key-group/maxParallelism contract")
    mesh = Mesh(np.asarray(devices), (AXIS,))
    return MeshPlan(mesh=mesh, num_shards=num_shards, slots_per_shard=slots_per_shard)
