"""Device mesh planning — the slot/TaskManager analogue.

The reference assigns each operator subtask a key-group range inside a
TaskManager slot (ref: runtime/taskexecutor/slot/TaskSlotTableImpl.java,
runtime/state/KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex).
Here a "subtask" is a TPU device in a 1-D ``jax.sharding.Mesh``; each
device owns a contiguous range of key shards, and keyed exchanges are XLA
collectives over the mesh axis (ICI within a slice, DCN across slices —
the sharding is the same, XLA picks the transport).

The mesh axis is named ``"d"`` throughout (data/devices); scaling to
multi-host is the same mesh built from ``jax.devices()`` across processes.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "d"

#: outer (cross-slice) axis of a hybrid ICI×DCN mesh — collectives over
#: AXIS stay inside a slice (ICI); nothing in the compiled step ever
#: reduces over this axis, because cross-slice residue is routed on the
#: HOST through the DCN exchange before ingest (exchange/dcn.py)
DCN_AXIS = "h"


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Static plan binding key shards to mesh devices.

    num_shards plays maxParallelism (fixed hash space, default 128);
    each device owns ``shards_per_device`` contiguous shards, i.e. the
    key-group range of that "subtask".
    """

    mesh: Mesh
    num_shards: int
    slots_per_shard: int

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def shards_per_device(self) -> int:
        return self.num_shards // self.n_devices

    @property
    def slots_per_device(self) -> int:
        return self.shards_per_device * self.slots_per_shard

    @property
    def total_slots(self) -> int:
        return self.num_shards * self.slots_per_shard

    @property
    def rows_per_device(self) -> int:
        return self.slots_per_device + 1  # + per-device dump row

    def shard_range(self, device_index: int) -> Tuple[int, int]:
        s = self.shards_per_device
        return (device_index * s, (device_index + 1) * s)

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def row_sharding(self) -> NamedSharding:
        """Sharding for state arrays: leading (device-blocked rows) axis."""
        return self.sharding(AXIS)

    def batch_sharding(self) -> NamedSharding:
        """Sharding for record batches: leading batch axis split across
        devices (arrival distribution, pre-keyBy)."""
        return self.sharding(AXIS)

    def device_of_slot(self, global_slots: np.ndarray) -> np.ndarray:
        return global_slots // self.slots_per_device

    def global_slot_to_row(self, global_slots: np.ndarray) -> np.ndarray:
        """Global slot id → row index in the (n_dev * rows_per_device)
        state array (each device block carries one extra dump row)."""
        dev = global_slots // self.slots_per_device
        return global_slots + dev  # + one dump row per preceding device


def make_mesh_plan(
    num_shards: int,
    slots_per_shard: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> MeshPlan:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if num_shards % n != 0:
        raise ValueError(
            f"state.num-key-shards ({num_shards}) must be a multiple of the "
            f"device count ({n}) — the key-group/maxParallelism contract")
    mesh = Mesh(np.asarray(devices), (AXIS,))
    return MeshPlan(mesh=mesh, num_shards=num_shards, slots_per_shard=slots_per_shard)


@dataclasses.dataclass(frozen=True)
class HybridMeshPlan(MeshPlan):
    """The ICI×DCN topology of one slice of a cross-host job
    (SNIPPETS.md [1] ``create_hybrid_device_mesh``: ICI inner axis, DCN
    outer axis). ``num_shards`` here is this process's LOCAL span —
    the operator contract is identical to a plain :class:`MeshPlan` —
    while the global fields expose the fleet-level shard math the
    host-side DCN router shares (exchange/partitioners.hybrid_route).

    The local mesh carries BOTH axes, (``DCN_AXIS``=1, ``AXIS``=n):
    every in-step collective names ``AXIS`` only, so keyBy shuffle
    bytes provably stay intra-slice — the outer axis exists so the
    compiled program's sharding layout is the hybrid one, and a future
    multi-controller global mesh (all slices in one Mesh) changes the
    axis SIZES, not the program."""

    n_processes: int = 1
    process_id: int = 0

    @property
    def global_num_shards(self) -> int:
        return self.num_shards * self.n_processes

    @property
    def shard_lo(self) -> int:
        """First global shard this slice owns (contiguous span)."""
        return self.process_id * self.num_shards

    def owner(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(B,) keys → (process, local device) — the one routing truth,
        delegated to exchange/partitioners.hybrid_route."""
        from flink_tpu.exchange.partitioners import hybrid_route

        return hybrid_route(keys, self.global_num_shards,
                            self.n_processes, self.n_devices)


def make_hybrid_mesh_plan(
    global_num_shards: int,
    slots_per_shard: int,
    n_processes: int,
    process_id: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> HybridMeshPlan:
    """This process's slice of the hybrid topology: a (1, n_local)
    local mesh with the DCN axis outermost, owning the contiguous
    global shard span ``[pid*spp, (pid+1)*spp)``."""
    from flink_tpu.utils.jaxcompat import hybrid_device_mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if global_num_shards % n_processes:
        raise ValueError(
            f"state.num-key-shards ({global_num_shards}) must divide by "
            f"cluster.num-processes ({n_processes}) — shards are the "
            "rescale unit (the key-group contract)")
    local_shards = global_num_shards // n_processes
    if local_shards % n:
        raise ValueError(
            f"per-process shard span ({local_shards}) must be a multiple "
            f"of the local device count ({n})")
    arr = hybrid_device_mesh((1, n), (1, 1), devices)
    mesh = Mesh(arr, (DCN_AXIS, AXIS))
    return HybridMeshPlan(
        mesh=mesh, num_shards=local_shards,
        slots_per_shard=slots_per_shard,
        n_processes=n_processes, process_id=process_id)
