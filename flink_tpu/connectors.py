"""File connectors: format-aware FileSource + exactly-once FileSink.

ref: flink-connectors/flink-connector-files — ``FileSource`` (FLIP-27
splits: one split per file, replayable positions) and ``FileSink``
(part files staged in-progress, visible on checkpoint commit; the
rename-on-commit discipline of SURVEY §3.9). Formats plug in via
``flink_tpu.formats.Format``; paths go through the FileSystem
abstraction, so any registered scheme works.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.api.sinks import Sink
from flink_tpu.api.sources import Source
from flink_tpu.formats import Format
from flink_tpu.fs import get_filesystem

__all__ = ["FileSource", "FileSink"]

Batch = Tuple[Dict[str, np.ndarray], np.ndarray]


@dataclasses.dataclass
class FileSource(Source):
    """One split per matching file; positions are batch indices within
    the split (replay restarts the file and skips already-consumed
    batches — the same replay contract every source here honors).
    ``ts_field`` names the event-time column (ms); absent, batches get
    ingest-time stamps like TextLineSource."""

    path: str                      # file, directory, or glob
    format: Format
    ts_field: Optional[str] = None
    batch_size: int = 65536

    def splits(self) -> List[str]:
        fs = get_filesystem(self.path)
        base = self.path
        if fs.exists(base) and fs.is_dir(base):
            return sorted(
                os.path.join(base, f) for f in fs.listdir(base)
                if not f.startswith("."))
        if any(ch in base for ch in "*?["):
            d, pat = os.path.split(base)
            if not fs.exists(d):
                return []
            return sorted(
                os.path.join(d, f) for f in fs.listdir(d)
                if fnmatch.fnmatch(f, pat))
        return [base] if fs.exists(base) else []

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        import time as _time

        fs = get_filesystem(split)
        with fs.open_read(split) as f:
            raw = f.read()
        if isinstance(raw, str):
            raw = raw.encode("utf-8")
        lines = raw.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        for index, lo in enumerate(range(0, len(lines), self.batch_size)):
            if index < start_pos:
                continue
            block = b"\n".join(lines[lo:lo + self.batch_size]) + b"\n"
            data = self.format.deserialize(block)
            if self.ts_field is not None:
                ts = np.asarray(data[self.ts_field], np.int64)
            else:
                now = np.int64(_time.time() * 1000)
                ts = np.full(len(next(iter(data.values()), [])),
                             now, np.int64)
            yield data, ts

    def bounded(self) -> bool:
        return True


class FileSink(Sink):
    """Exactly-once, format-serialized part files. Rows buffer in
    memory per epoch; ``prepare_commit`` writes+fsyncs a staged part
    file, ``notify_checkpoint_complete`` atomically renames it into
    ``committed/`` (the transaction point). Rolling: a staged epoch
    splits into numbered part files every ``rolling_records`` rows, so
    downstream consumers see bounded files (ref: FileSink's
    RollingPolicy + the TwoPhaseCommitSinkFunction discipline; same
    restore/abort contract as FileTransactionalSink — staged rows ride
    the checkpoint so a cleaned-up attempt can reconstruct them)."""

    def __init__(self, directory: str, format: Format,
                 rolling_records: int = 1_000_000) -> None:
        self.dir = directory
        self.format = format
        self.rolling_records = max(1, rolling_records)
        self._fs = get_filesystem(directory)
        self._staged_dir = os.path.join(directory, "staged")
        self._committed_dir = os.path.join(directory, "committed")
        self._fs.mkdirs(self._staged_dir)
        self._fs.mkdirs(self._committed_dir)
        self._pending: List[Dict[str, np.ndarray]] = []

    # -- write path ------------------------------------------------------
    def write(self, batch: Dict[str, np.ndarray]) -> None:
        cols = {k: np.asarray(v) for k, v in batch.items()
                if k in self.format.fields}
        if cols and len(next(iter(cols.values()))):
            self._pending.append(cols)

    def _concat_pending(self) -> Optional[Dict[str, np.ndarray]]:
        if not self._pending:
            return None
        out = {k: np.concatenate([b[k] for b in self._pending])
               for k in self._pending[0]}
        self._pending = []
        return out

    def _part_name(self, cid: int, part: int) -> str:
        return f"part-{cid:010d}-{part:04d}"

    def prepare_commit(self, checkpoint_id: int) -> None:
        data = self._concat_pending()
        if data is None:
            return
        n = len(next(iter(data.values())))
        part = 0
        for lo in range(0, n, self.rolling_records):
            chunk = {k: v[lo:lo + self.rolling_records]
                     for k, v in data.items()}
            payload = self.format.serialize(chunk)
            path = os.path.join(self._staged_dir,
                                self._part_name(checkpoint_id, part))
            tmp = path + ".tmp"
            with self._fs.open_write(tmp) as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            self._fs.rename(tmp, path)
            part += 1

    # -- commit protocol -------------------------------------------------
    def _staged_parts(self) -> List[Tuple[int, str]]:
        out = []
        for f in self._fs.listdir(self._staged_dir):
            if f.startswith("part-") and not f.endswith(".tmp"):
                out.append((int(f.split("-")[1]), f))
        return sorted(out)

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for cid, name in self._staged_parts():
            if cid <= checkpoint_id:
                src = os.path.join(self._staged_dir, name)
                dst = os.path.join(self._committed_dir, name)
                if self._fs.exists(dst):
                    self._fs.delete(src)  # idempotent replayed commit
                else:
                    self._fs.rename(src, dst)

    def snapshot_staged(self) -> Any:
        """Staged part BYTES ride in the checkpoint (same rationale as
        FileTransactionalSink: an aborted attempt may have deleted the
        staged files; the covering checkpoint must reconstruct them)."""
        parts = {}
        for cid, name in self._staged_parts():
            with self._fs.open_read(
                    os.path.join(self._staged_dir, name)) as f:
                raw = f.read()
            parts[name] = raw if isinstance(raw, bytes) else raw.encode()
        return {"parts": parts}

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        self._pending = []
        for name, payload in (staged or {}).get("parts", {}).items():
            path = os.path.join(self._staged_dir, name)
            if self._fs.exists(path):
                continue
            tmp = path + ".tmp"
            with self._fs.open_write(tmp) as f:
                f.write(payload)
            self._fs.rename(tmp, path)

    def abort_uncommitted(self) -> None:
        """Crash before the covering checkpoint: staged parts of the
        dead attempt must never become visible."""
        for _, name in self._staged_parts():
            self._fs.delete(os.path.join(self._staged_dir, name))
        self._pending = []

    # -- reading back (tests / consumers) -------------------------------
    def committed_batches(self) -> List[Dict[str, np.ndarray]]:
        out = []
        for name in sorted(self._fs.listdir(self._committed_dir)):
            with self._fs.open_read(
                    os.path.join(self._committed_dir, name)) as f:
                raw = f.read()
            out.append(self.format.deserialize(
                raw if isinstance(raw, bytes) else raw.encode()))
        return out
