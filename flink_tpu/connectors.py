"""File connectors: format-aware FileSource + exactly-once FileSink.

ref: flink-connectors/flink-connector-files — ``FileSource`` (FLIP-27
splits: one split per file, replayable positions) and ``FileSink``
(part files staged in-progress, visible on checkpoint commit; the
rename-on-commit discipline of SURVEY §3.9). Formats plug in via
``flink_tpu.formats.Format``; paths go through the FileSystem
abstraction, so any registered scheme works.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.api.sinks import Sink, TwoPhaseCommitSink
from flink_tpu.api.sources import Source
from flink_tpu.formats import Format
from flink_tpu.fs import get_filesystem

__all__ = ["FileSource", "FileSink", "SocketSource"]

Batch = Tuple[Dict[str, np.ndarray], np.ndarray]


@dataclasses.dataclass
class FileSource(Source):
    """One split per matching file; positions are batch indices within
    the split (replay restarts the file and skips already-consumed
    batches — the same replay contract every source here honors).
    ``ts_field`` names the event-time column (ms); absent, batches get
    ingest-time stamps like TextLineSource."""

    path: str                      # file, directory, or glob
    format: Format
    ts_field: Optional[str] = None
    batch_size: int = 65536

    def splits(self) -> List[str]:
        fs = get_filesystem(self.path)
        base = self.path
        if fs.exists(base) and fs.is_dir(base):
            return sorted(
                os.path.join(base, f) for f in fs.listdir(base)
                if not f.startswith("."))
        if any(ch in base for ch in "*?["):
            d, pat = os.path.split(base)
            if not fs.exists(d):
                return []
            return sorted(
                os.path.join(d, f) for f in fs.listdir(d)
                if fnmatch.fnmatch(f, pat))
        return [base] if fs.exists(base) else []

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        import time as _time

        fs = get_filesystem(split)
        with fs.open_read(split) as f:
            raw = f.read()
        if isinstance(raw, str):
            raw = raw.encode("utf-8")
        if getattr(self.format, "binary", False):
            # self-framing binary format (columnar): the format owns
            # block iteration — line-splitting would corrupt it. The
            # replay position is the stored-block index; skip= elides
            # decoding of already-consumed blocks.
            for data in self.format.iter_batches(raw, skip=start_pos):
                if self.ts_field is not None:
                    ts = np.asarray(data[self.ts_field], np.int64)
                else:
                    now = np.int64(_time.time() * 1000)
                    ts = np.full(len(next(iter(data.values()), [])),
                                 now, np.int64)
                yield data, ts
            return
        lines = raw.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        for index, lo in enumerate(range(0, len(lines), self.batch_size)):
            if index < start_pos:
                continue
            block = b"\n".join(lines[lo:lo + self.batch_size]) + b"\n"
            data = self.format.deserialize(block)
            if self.ts_field is not None:
                ts = np.asarray(data[self.ts_field], np.int64)
            else:
                now = np.int64(_time.time() * 1000)
                ts = np.full(len(next(iter(data.values()), [])),
                             now, np.int64)
            yield data, ts

    @property
    def bounded(self) -> bool:
        return True


class FileSink(TwoPhaseCommitSink):
    """Exactly-once, format-serialized part files on the generalized
    TwoPhaseCommitSink protocol (api/sinks.py). Rows buffer in memory
    per epoch; the barrier stages them as fsynced part files under
    ``staged/``; checkpoint completion atomically renames them into
    ``committed/`` (the transaction point). Rolling: a staged epoch
    splits into numbered part files every ``rolling_records`` rows, so
    downstream consumers see bounded files (ref: FileSink's
    RollingPolicy + the TwoPhaseCommitSinkFunction discipline; staged
    part BYTES ride the checkpoint so a cleaned-up attempt can
    reconstruct them — the FileTransactionalSink rationale).

    Part names are ATTEMPT-EPOCH-qualified —
    ``part-<cid>-<part>.e<epoch>`` (the same ``chk-<id>.e<epoch>``
    fencing discipline checkpoint storage uses): a deposed attempt
    restarting mid-commit renames to ITS epoch's name, never over a
    successor's committed part; readers resolve duplicates of one
    (cid, part) to the highest epoch."""

    def __init__(self, directory: str, format: Format,
                 rolling_records: int = 1_000_000) -> None:
        self.dir = directory
        self.format = format
        self.rolling_records = max(1, rolling_records)
        self._fs = get_filesystem(directory)
        self._staged_dir = os.path.join(directory, "staged")
        self._committed_dir = os.path.join(directory, "committed")
        self._fs.mkdirs(self._staged_dir)
        self._fs.mkdirs(self._committed_dir)
        self._pending: List[Dict[str, np.ndarray]] = []
        self._epoch = 0

    def set_attempt_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    # -- write path ------------------------------------------------------
    def write(self, batch: Dict[str, np.ndarray]) -> None:
        cols = {k: np.asarray(v) for k, v in batch.items()
                if k in self.format.fields}
        if cols and len(next(iter(cols.values()))):
            self._pending.append(cols)

    def _concat_pending(self) -> Optional[Dict[str, np.ndarray]]:
        if not self._pending:
            return None
        out = {k: np.concatenate([b[k] for b in self._pending])
               for k in self._pending[0]}
        self._pending = []
        return out

    def _part_name(self, cid: int, part: int) -> str:
        return f"part-{cid:010d}-{part:04d}.e{self._epoch}"

    @staticmethod
    def _parse_part(name: str) -> Optional[Tuple[int, int, int]]:
        """``part-<cid>-<part>[.e<epoch>]`` → (cid, part, epoch); None
        for tmp files and foreign names. Suffixless names (pre-epoch
        directories) read as epoch 0."""
        if not name.startswith("part-") or name.endswith(".tmp"):
            return None
        core, _, esuf = name.partition(".e")
        bits = core.split("-")
        try:
            return (int(bits[1]), int(bits[2]),
                    int(esuf) if esuf else 0)
        except (IndexError, ValueError):
            return None

    # -- TwoPhaseCommitSink contract -------------------------------------
    def drop_pending(self) -> None:
        self._pending = []

    def stage_transaction(self, cid: int) -> bool:
        data = self._concat_pending()
        if data is None:
            return False
        n = len(next(iter(data.values())))
        part = 0
        for lo in range(0, n, self.rolling_records):
            chunk = {k: v[lo:lo + self.rolling_records]
                     for k, v in data.items()}
            payload = self.format.serialize(chunk)
            from flink_tpu.fs import write_atomic

            # tmp + fsync + rename through the seam (ENOSPC-retried,
            # CrashFS-recorded): the staged part is durable before the
            # pre-commit state references it
            write_atomic(self._fs, os.path.join(
                self._staged_dir, self._part_name(cid, part)), payload)
            part += 1
        return True

    def _staged_parts(self) -> List[Tuple[int, str]]:
        out = []
        for f in self._fs.listdir(self._staged_dir):
            parsed = self._parse_part(f)
            if parsed is not None:
                out.append((parsed[0], f))
        return sorted(out)

    def staged_transaction_ids(self) -> List[int]:
        return sorted({cid for cid, _ in self._staged_parts()})

    def _committed_keys(self) -> set:
        """(cid, part) pairs committed at ANY epoch — the idempotence
        check must see a part another attempt already published."""
        out = set()
        for f in self._fs.listdir(self._committed_dir):
            parsed = self._parse_part(f)
            if parsed is not None:
                out.add(parsed[:2])
        return out

    def commit_transaction(self, cid: int) -> None:
        committed = self._committed_keys()
        staged = [(self._parse_part(name), name)
                  for c, name in self._staged_parts() if c == cid]
        # one winner per (cid, part): the highest staged epoch — a
        # deposed attempt's duplicate staging of the same transaction
        # loses to its successor's, so exactly one file publishes
        winners: Dict[int, Tuple[int, str]] = {}
        for (_, part, epoch), name in staged:
            cur = winners.get(part)
            if cur is None or epoch > cur[0]:
                winners[part] = (epoch, name)
        for (_, part, epoch), name in staged:
            src = os.path.join(self._staged_dir, name)
            if name != winners[part][1] or (cid, part) in committed:
                self._fs.delete(src)  # deposed duplicate or idempotent
                # replayed commit — possibly by another attempt's
                # epoch; never clobber
            else:
                self._fs.rename(src, os.path.join(
                    self._committed_dir, name))

    def abort_transaction(self, cid: int) -> None:
        for c, name in self._staged_parts():
            # epoch fence: a part staged by a HIGHER attempt epoch is a
            # successor's live transaction — a deposed attempt's late
            # abort must not delete it (mirror of topic.py abort)
            if c == cid and self._parse_part(name)[2] <= self._epoch:
                self._fs.delete(os.path.join(self._staged_dir, name))

    def snapshot_transaction(self, cid: int) -> Any:
        parts = {}
        for c, name in self._staged_parts():
            if c != cid:
                continue
            with self._fs.open_read(
                    os.path.join(self._staged_dir, name)) as f:
                raw = f.read()
            parts[name] = raw if isinstance(raw, bytes) else raw.encode()
        return {"parts": parts}

    def rebuild_transaction(self, cid: int, payload: Any) -> None:
        from flink_tpu.fs import write_atomic

        for name, data in (payload or {}).get("parts", {}).items():
            path = os.path.join(self._staged_dir, name)
            if self._fs.exists(path):
                continue
            write_atomic(self._fs, path, data)

    # -- reading back (tests / consumers) -------------------------------
    def committed_batches(self) -> List[Dict[str, np.ndarray]]:
        best: Dict[Tuple[int, int], Tuple[int, str]] = {}
        for name in self._fs.listdir(self._committed_dir):
            parsed = self._parse_part(name)
            if parsed is None:
                continue
            cid, part, epoch = parsed
            cur = best.get((cid, part))
            if cur is None or epoch > cur[0]:
                # duplicate (cid, part) across attempt epochs: the
                # highest epoch wins (the checkpoint fence resolution)
                best[(cid, part)] = (epoch, name)
        out = []
        for key in sorted(best):
            with self._fs.open_read(os.path.join(
                    self._committed_dir, best[key][1])) as f:
                raw = f.read()
            out.append(self.format.deserialize(
                raw if isinstance(raw, bytes) else raw.encode()))
        return out


class SocketSource(Source):
    """Line-framed TCP ingest source (ref: socketTextStream +
    SocketSourceFunction; transport per SURVEY §3.10 item 3 — the C
    reader in native/codec.cc, with a pure-Python fallback). The source
    LISTENS; a producer connects and streams newline-separated records;
    the stream ends when the producer disconnects.

    Like the reference's socket source, this is NOT replayable: a
    restore cannot re-read a socket, so exactly-once holds only from
    ingest onward (``open_split`` ignores ``start_pos``). Timestamps
    come from ``ts_field`` when the format provides it, else ingest
    time."""

    def __init__(self, port: int = 0, format: Optional[Format] = None,
                 ts_field: Optional[str] = None,
                 block_bytes: int = 1 << 20,
                 poll_ms: int = 100) -> None:
        self.format = format
        self.ts_field = ts_field
        self.block_bytes = block_bytes
        self.poll_ms = poll_ms
        from flink_tpu.native_codec import NativeSocketReader

        self._reader = NativeSocketReader.create(port)
        if self._reader is None:
            self._reader = _PySocketReader(port)
        self.port = self._reader.port

    def splits(self) -> List[str]:
        return ["socket"]

    @property
    def bounded(self) -> bool:
        return True  # ends when the producer disconnects

    def _empty_batch(self):
        """Zero-length but SCHEMA-TYPED columns: downstream chains index
        columns on every batch, so an idle tick must present the same
        shape as a data batch."""
        if self.format is not None:
            return self.format.deserialize(b"")
        return {"line": np.array([], dtype=object)}

    def open_split(self, split: str, start_pos: int = 0):
        import time as _time

        # wait for a producer — yielding an empty batch per poll hands
        # control back to the driver between next() calls, so cancel /
        # stop-with-savepoint work while nobody has connected yet
        while self._reader.accept(self.poll_ms) == 0:
            yield self._empty_batch(), np.zeros(0, np.int64)
        while True:
            block = self._reader.read_block(self.block_bytes, self.poll_ms)
            if block is None:
                break  # producer disconnected
            if not block:
                # timeout with no complete line: emit an empty batch so
                # the driver keeps its loop (watermarks/checkpoints)
                # alive on an idle socket
                yield self._empty_batch(), np.zeros(0, np.int64)
                continue
            if self.format is not None:
                data = self.format.deserialize(block)
            else:
                lines = block.decode("utf-8", "replace").splitlines()
                data = {"line": np.array(lines, dtype=object)}
            n = len(next(iter(data.values()), []))
            if self.ts_field is not None and self.ts_field in data:
                ts = np.asarray(data[self.ts_field], np.int64)
            else:
                ts = np.full(n, np.int64(_time.time() * 1000))
            yield data, ts
        self._reader.close()


class _PySocketReader:
    """Pure-Python fallback matching NativeSocketReader's contract."""

    def __init__(self, port: int = 0) -> None:
        import socket

        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", port))
        self._srv.listen(1)
        self._conn = None
        self._carry = b""

    @property
    def port(self) -> int:
        return self._srv.getsockname()[1]

    def accept(self, timeout_ms: int = 100) -> int:
        import socket

        if self._conn is not None:
            return 1
        self._srv.settimeout(timeout_ms / 1000)
        try:
            self._conn, _ = self._srv.accept()
        except socket.timeout:
            return 0
        return 1

    def read_block(self, cap: int = 1 << 20,
                   timeout_ms: int = 100) -> Optional[bytes]:
        import socket

        self._conn.settimeout(timeout_ms / 1000)
        buf = self._carry
        while True:
            nl = buf.rfind(b"\n")
            if nl >= 0 and (len(buf) >= cap or nl + 1 >= cap):
                self._carry = buf[nl + 1:]
                return buf[:nl + 1]
            if nl < 0 and len(buf) >= cap:
                # single line longer than cap: same loud contract as the
                # native reader (never buffer unboundedly)
                raise IOError(
                    f"socket reader error (a line exceeded {cap} bytes)")
            try:
                chunk = self._conn.recv(max(cap - len(buf), 1))
            except socket.timeout:
                if nl >= 0:
                    self._carry = buf[nl + 1:]
                    return buf[:nl + 1]
                self._carry = buf
                return b""
            if not chunk:
                self._carry = b""
                return buf[:nl + 1] if nl >= 0 else None
            buf += chunk

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
        self._srv.close()
