"""Static analysis — catch correctness bugs before the first record flows.

Three planes (ref: the validation pass of Flink's StreamGraph
translation — StreamGraphGenerator / StreamingJobGraphGenerator reject
malformed graphs at compile time, SURVEY §3.2; bounded-execution
validation, §3.6 — generalized into a rule engine):

- **Plan analysis** (``plan_rules.py``): linear rules over a lowered
  ``ExecutionPlan`` + its ``Configuration`` — misconfigurations that
  would otherwise fail minutes into a run (unbounded source in batch
  mode, two writers on one log topic, fault rules that match nothing)
  or silently corrupt results (event-time windows with no watermark
  strategy, non-transactional sinks under exactly-once). The driver
  runs every plane automatically at submit (``analysis.fail-on``);
  ``python -m flink_tpu analyze`` runs them standalone.

- **Dataflow analysis** (``dataflow.py``): ONE topological abstract
  interpretation propagating three lattices edge-by-edge — record
  schema (source declarations + compiler-recorded op schemas + abstract
  evaluation of chain fns on empty typed batches), state-growth bounds
  (bounded-by-geometry with a bytes-per-key estimate vs unbounded, from
  assigner/trigger/evictor/gap/skip-strategy facts), and watermark
  capability (event / processing / no time axis per leg). The dataflow
  rules (field-not-in-schema, union mismatch, unbounded growth, stalled
  legs, exactly-once taint through log topics, state budgets) read the
  propagated facts; ``analyze --explain`` prints them per node.

- **Repo AST lints** (``pylints.py`` over ``callgraph.py``): a
  pure-stdlib INTERPROCEDURAL pass over the codebase itself — the
  linted files are indexed into one project-wide call graph (defs,
  methods resolved through the receiver's inferred self-type,
  module-qualified calls, lock/lease binding types) and the protocol
  rules walk its edges: tracer leaks in jit kernels (host conversions /
  Python branches on traced values, followed through the helpers the
  traced arguments flow into — the failure class PROFILE §8.1's design
  rules exist to prevent), fault-point drift in BOTH directions
  (unknown ``faults.fire`` literals and registered points nothing
  fires), config/metric name drift, unlocked shared-state writes in
  HostPool task closures at any call depth (lock guards recognized by
  binding type), raw durable writes bypassing the fs.py seam,
  lock-order (ABBA) cycles with both acquisition paths named, and
  fenced-record publications a deposed leaseholder could still make
  (no lease verify()/renew on the path). Run via ``python -m flink_tpu
  lint [--plane NAME]`` or ``tools/lint.py``; the dogfood gate
  (tests/test_analysis.py) keeps the shipped tree at zero findings and
  the full pass under a 3 s wall-clock budget.

RULES.md is GENERATED from the registrations (``docs.py`` +
``tools/gen_rules.py``) with a tier-1 staleness gate, so a rule cannot
ship undocumented.

Honest scope: the dataflow plane has no cross-function taint (a field
smuggled through opaque user state is invisible), no symbolic shapes
(state estimates use declared config geometry, not data), and schema
facts stop at the first chain that is opaque to empty-batch
evaluation. The repo lints DO cross functions, but the walks are
capped (8 call hops for tracer taint, 6 for pool writes and fence
walks), only name / self-method / module-qualified calls resolve (no
duck-typed dispatch), and lock identity is syntactic — a lock aliased
through a variable or passed as a bare parameter falls back to
name-substring recognition.
"""
from flink_tpu.analysis.core import (
    AnalysisError,
    Finding,
    analyze,
    analyze_config,
    render_findings,
)

__all__ = ["AnalysisError", "Finding", "analyze", "analyze_config",
           "render_findings"]
