"""Static analysis — catch correctness bugs before the first record flows.

Two planes (ref: the validation pass of Flink's StreamGraph translation
— StreamGraphGenerator / StreamingJobGraphGenerator reject malformed
graphs at compile time, SURVEY §3.2; bounded-execution validation,
§3.6 — generalized into a rule engine):

- **Plan analysis** (``plan_rules.py``): walks a lowered
  ``ExecutionPlan`` + its ``Configuration`` and reports structured
  findings — misconfigurations that would otherwise fail minutes into a
  run (unbounded source in batch mode, two writers on one log topic,
  fault rules that match nothing) or silently corrupt results
  (event-time windows with no watermark strategy, non-transactional
  sinks under exactly-once). The driver runs it automatically at submit
  (``analysis.fail-on``); ``python -m flink_tpu analyze`` runs it
  standalone.

- **Repo AST lints** (``pylints.py``): a pure-stdlib ``ast`` pass over
  the codebase itself — tracer leaks in jit kernels (host conversions /
  Python branches on traced values, the failure class PROFILE §8.1's
  design rules exist to prevent), fault-point literals drifting from
  the ``faults.py`` registry, config/metric name drift. Run via
  ``python -m flink_tpu lint`` or ``tools/lint.py``; the dogfood gate
  (tests/test_analysis.py) keeps the shipped tree at zero findings.

Honest scope: a LINEAR rule engine — each rule is one walk over the
plan or the AST. No dataflow analysis, no abstract interpretation, no
cross-function taint; the tracer-leak lint tracks only direct uses of
a jit-traced parameter inside its own kernel body.
"""
from flink_tpu.analysis.core import (
    AnalysisError,
    Finding,
    analyze,
    analyze_config,
    render_findings,
)

__all__ = ["AnalysisError", "Finding", "analyze", "analyze_config",
           "render_findings"]
