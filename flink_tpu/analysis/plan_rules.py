"""The plan-analysis rule catalog.

Every rule is a single walk over the lowered ``ExecutionPlan`` and/or
the ``Configuration`` — the static preconditions of guarantees PRs 1–3
proved dynamically (exactly-once, job chaining, bounded execution),
checked before the first record flows (ref: the validation layer of
StreamGraph translation, SURVEY §3.2/§3.6).

Severity contract (what ``analysis.fail-on`` keys on):

- **error** — the job WILL fail or corrupt output at runtime: an
  unbounded source in batch mode, a window that can never fire, two
  writers on one log topic, a chaos rule injecting nothing, keyed
  state with no key exchange, checkpointing in batch mode.
- **warn** — correctness smells that depend on intent: event-time
  windows riding the default watermark strategy, non-transactional
  sinks under exactly-once checkpointing, config keys outside the
  declared grammar (typos).
"""
from __future__ import annotations

import difflib
import fnmatch
import os
from typing import Any, Iterable, Iterator, List, Set

from flink_tpu.analysis.core import Finding, config_rule, plan_rule

# a rule fills message/location; analyze() stamps the registered
# rule id + severity on every finding it yields
def _f(message: str, fix: str = "", node=None, node_name: str = "",
       file: str = "", line: int = 0) -> Finding:
    return Finding(rule="", severity="warn", message=message, fix=fix,
                   node=node, node_name=node_name, file=file, line=line)


def _upstream_sources(plan, nid: int) -> Iterator[Any]:
    """Source nodes transitively feeding ``nid``."""
    upstream = {n: [] for n in plan.nodes}
    for n in plan.nodes.values():
        for d in n.downstream:
            upstream[d].append(n.id)
    seen: Set[int] = set()
    stack = [nid]
    while stack:
        cur = stack.pop()
        for u in upstream[cur]:
            if u in seen:
                continue
            seen.add(u)
            node = plan.nodes[u]
            if node.kind == "source":
                yield node
            else:
                stack.append(u)


def _runtime_mode(config) -> str:
    from flink_tpu.config import ExecutionOptions

    return str(config.get(ExecutionOptions.RUNTIME_MODE)).strip().lower()


# kinds whose operator keys state by node.key_field and therefore needs
# the keyBy exchange the lowering folds into it (keyed_input)
KEYED_KINDS = frozenset((
    "window", "evicting_window", "session", "count_window", "process",
    "cep", "global_agg",
))

# kinds that evaluate event-time semantics against the watermark clock
_EVENT_TIME_KINDS = frozenset((
    "window", "evicting_window", "window_all", "join", "session", "cep",
))


def _is_event_time(node) -> bool:
    if node.kind in ("session", "cep"):
        return True  # session gaps / CEP within-windows are event-time
    assigner = getattr(node.window_transform, "assigner", None)
    if assigner is None:
        return False
    return bool(getattr(assigner, "is_event_time", True))


@plan_rule("EVENT_TIME_NO_WATERMARK", "warn",
           fix="pass a WatermarkStrategy to from_source()")
def event_time_no_watermark(plan, config) -> Iterable[Finding]:
    """Event-time op fed by a source with no explicit watermark
    strategy: the pipeline-default monotonous clock treats ANY
    out-of-order timestamp as late and silently drops it."""
    for node in plan.nodes.values():
        if node.kind not in _EVENT_TIME_KINDS or not _is_event_time(node):
            continue
        for src in _upstream_sources(plan, node.id):
            if src.watermark_strategy is None:
                yield _f(
                    f"event-time {node.kind} {node.name!r} is fed by "
                    f"source {src.name!r} with no watermark strategy — "
                    "out-of-order records will be dropped as late under "
                    "the default monotonous clock",
                    fix="pass a WatermarkStrategy to from_source(), e.g. "
                        "WatermarkStrategy.for_bounded_out_of_orderness("
                        "ms)",
                    node=node.id, node_name=node.name)


@plan_rule("NON_TRANSACTIONAL_SINK", "warn",
           fix="use a TwoPhaseCommitSink or disable checkpointing")
def non_transactional_sink(plan, config) -> Iterable[Finding]:
    """Checkpointing is on (exactly-once intended) but a sink writes
    through: a recovery replays the uncheckpointed tail into it —
    at-least-once output, duplicates on every restore."""
    from flink_tpu.api.sinks import sink_is_transactional
    from flink_tpu.config import CheckpointingOptions

    if config.get(CheckpointingOptions.INTERVAL) <= 0:
        return
    for node in plan.nodes.values():
        if node.kind != "sink" or node.sink is None:
            continue
        if not sink_is_transactional(node.sink):
            yield _f(
                f"sink {node.name!r} ({type(node.sink).__name__}) is not "
                "transactional but execution.checkpointing.interval is "
                "set — recovery will replay the un-checkpointed tail "
                "into it (duplicates; at-least-once, not exactly-once)",
                fix="use a TwoPhaseCommitSink (LogSink, FileSink, "
                    "FileTransactionalSink) or disable checkpointing",
                node=node.id, node_name=node.name)


@plan_rule("UNBOUNDED_SOURCE_IN_BATCH", "error",
           fix="bound the source or run in streaming mode")
def unbounded_source_in_batch(plan, config) -> Iterable[Finding]:
    """Batch (bounded) mode requires every source to end: stages run to
    completion in topological waves — an unbounded source never lets
    its stage finish."""
    from flink_tpu.api.sources import source_is_bounded

    if _runtime_mode(config) != "batch":
        return
    for sid in plan.sources:
        node = plan.nodes[sid]
        if node.source is not None and not source_is_bounded(node.source):
            yield _f(
                f"source {node.name!r} is unbounded under "
                "execution.runtime-mode=batch — its stage can never "
                "run to completion",
                fix="bound the source (is_bounded=True / finite "
                    "generator) or run in streaming mode",
                node=node.id, node_name=node.name)


@plan_rule("KEYED_OP_WITHOUT_KEYBY", "error",
           fix="insert .key_by(...) before the stateful op")
def keyed_op_without_keyby(plan, config) -> Iterable[Finding]:
    """A keyed stateful op whose input edge never went through a keyBy
    exchange: state would partition on whatever column happens to share
    the key field's name — wrong results or a missing-column crash."""
    for node in plan.nodes.values():
        if node.kind in KEYED_KINDS and not node.keyed_input:
            yield _f(
                f"keyed {node.kind} {node.name!r} is reachable without "
                "a keyBy exchange — its state partitions on an "
                "undeclared key column",
                fix="insert .key_by(column_or_fn) immediately before "
                    "the stateful op",
                node=node.id, node_name=node.name)


@plan_rule("WINDOW_WITHOUT_FIRE_BOUND", "error",
           fix="set a trigger or use a time-bounded assigner")
def window_without_fire_bound(plan, config) -> Iterable[Finding]:
    """A GlobalWindows op with no trigger never fires: every record is
    state forever — unbounded growth and zero output."""
    from flink_tpu.api.windowing import GlobalWindows

    for node in plan.nodes.values():
        wt = node.window_transform
        if wt is None or not isinstance(
                getattr(wt, "assigner", None), GlobalWindows):
            continue
        if getattr(wt, "trigger", None) is None:
            yield _f(
                f"{node.kind} {node.name!r} uses GlobalWindows with no "
                "trigger — it can never fire, and per-key state grows "
                "without bound",
                fix="set a trigger (.trigger(CountTrigger.of(n))) or "
                    "use count_window(n) / a time-bounded assigner",
                node=node.id, node_name=node.name)


@plan_rule("LOG_TOPIC_MULTI_WRITER", "error",
           fix="lease disjoint partitions (owned_partitions + "
               "producer_id), or one LogSink per topic")
def log_topic_multi_writer(plan, config) -> Iterable[Finding]:
    """Multiple LogSinks on one topic directory WITHOUT disjoint
    partition leases: the embedded log serializes appends per
    PARTITION via fenced writer leases (log/bus.py), so N sinks with
    pairwise-disjoint ``owned_partitions`` (distinct producer ids) are
    legal — but two un-leased writers, or two leases overlapping on a
    partition, roll back each other's staged transactions."""
    try:
        from flink_tpu.log.connectors import LogSink
    except Exception:  # log subsystem not importable: nothing to check
        return
    by_topic = {}
    for node in plan.nodes.values():
        if node.kind == "sink" and isinstance(node.sink, LogSink):
            topic = os.path.realpath(str(node.sink.path))
            by_topic.setdefault(topic, []).append(node)
    for topic, nodes in by_topic.items():
        if len(nodes) < 2:
            continue
        appenders = [n.sink._appender for n in nodes]
        leased = all(a.writer_id for a in appenders)
        owners = {}
        overlap = set()
        for n, a in zip(nodes, appenders):
            for p in a.owned:
                if p in owners:
                    overlap.add(p)
                owners[p] = n
        distinct_ids = len({a.writer_id for a in appenders}) == len(
            appenders)
        if leased and distinct_ids and not overlap:
            continue  # disjoint leased partitions: legal multi-writer
        names = ", ".join(f"{n.id} ({n.name!r})" for n in nodes)
        if leased and overlap:
            why = (f"their leased partition sets overlap on "
                   f"{sorted(overlap)} — a partition has ONE writer; "
                   "the lease fence will depose one of them mid-run")
        elif leased:
            why = ("they share a producer_id — writer-scoped markers "
                   "and leases would collide")
        else:
            why = ("they hold no partition leases — un-leased "
                   "concurrent appenders roll back each other's "
                   "staged transactions")
        for node in nodes:
            yield _f(
                f"log topic {topic!r} has {len(nodes)} writers in "
                f"this plan (sink nodes {names}) and {why}",
                fix="give each sink disjoint owned_partitions with a "
                    "distinct producer_id (fenced leases), or give "
                    "each its own topic / union the streams into ONE "
                    "LogSink",
                node=node.id, node_name=node.name)


@config_rule("STORAGE_LOCAL_LOCKS_ON_REMOTE", "warn",
             fix="keep high-availability.dir and log.dir on local "
                 "(file://) paths or a conditional-put scheme "
                 "(objstore://), or accept the documented "
                 "degradation: read-check-write acquisition races are "
                 "then bounded only by epoch fencing at the next "
                 "verify, not prevented")
def storage_local_locks_on_remote(plan, config) -> Iterable[Finding]:
    """Lock-dependent storage on a non-``file`` scheme WITHOUT
    conditional writes: the O_EXCL + rename-first lock discipline (HA
    leader-election leases, the log tier's writer-lease acquisition
    locks and maintenance locks) is LOCAL-filesystem-only —
    ``os.open(O_CREAT|O_EXCL)`` has no remote equivalent here. A
    scheme whose registered driver advertises ``conditional_put``
    (``fs.cas_capable`` — the objstore driver's ``put_if`` CAS) is
    QUIET: every lock-dependent path ports onto compare-and-swap
    there, which PREVENTS the race rather than bounding it. On any
    other remote scheme acquisition degrades to read-check-write
    (PR 9/11 honest residue): two racing acquirers can both believe
    they won until the next epoch verify rejects one. Flag the intent
    early, at submit, instead of as a once-a-month double-leader
    incident. Driver-aware: probes the scheme's REGISTERED filesystem,
    so an out-of-tree driver that grows CAS silences this rule by
    declaring it."""
    from flink_tpu.config import HighAvailabilityOptions, LogOptions
    from flink_tpu.fs import cas_capable, get_filesystem

    checks = (
        ("high-availability.dir",
         str(config.get(HighAvailabilityOptions.HA_DIR)),
         "leader-election lease steals + the durable session registry"),
        ("log.dir", str(config.get(LogOptions.DIR)),
         "per-partition writer-lease acquisition locks and topic "
         "maintenance locks"),
    )
    for key, value, what in checks:
        v = value.strip()
        scheme, sep, _ = v.partition("://")
        if not sep or scheme == "file":
            continue
        try:
            if cas_capable(get_filesystem(v)):
                continue  # CAS replaces the lock: race PREVENTED
        except ValueError:
            pass  # unregistered scheme: fails later, warn here too
        yield _f(
            f"{key}={v!r} resolves to scheme {scheme!r}, whose driver "
            f"offers no conditional-put: the O_EXCL + rename-first "
            f"lock discipline protecting {what} is local-filesystem-"
            "only — on this scheme acquisition degrades to "
            "read-check-write, fenced only after the fact by lease "
            "epochs",
            fix="move the directory to a shared LOCAL filesystem "
                "(file:// / bare path) or a conditional-put scheme "
                "(objstore://), or accept the degradation knowingly "
                "(single-acquirer operational discipline)")


@config_rule("LOG_RETENTION_UNSAFE", "warn",
             fix="set log.retention.ms >= "
                 "execution.checkpointing.interval (or disable one)")
def log_retention_unsafe(plan, config) -> Iterable[Finding]:
    """A retention window shorter than the checkpoint interval under
    checkpointing: consumer-group offsets only advance at checkpoint
    complete, so the dynamic safety floor pins every segment a group
    still needs — but a retention pass between a consumer's start and
    its FIRST completed checkpoint sees no group floor to respect for
    groups that have not committed yet, and a window below the
    checkpoint cadence guarantees the topic is perpetually at the
    floor (retention that can never drop anything, or drops history a
    brand-new group expected to backfill from)."""
    from flink_tpu.config import CheckpointingOptions, LogOptions

    retention_ms = int(config.get(LogOptions.RETENTION_MS))
    interval = int(config.get(CheckpointingOptions.INTERVAL))
    if retention_ms <= 0 or interval <= 0:
        return
    if retention_ms < interval:
        yield _f(
            f"log.retention.ms={retention_ms} is shorter than "
            f"execution.checkpointing.interval={interval}: group "
            "committed offsets (the retention safety floor) only "
            "advance at checkpoint complete, so retention this "
            "aggressive either never drops anything (floor-pinned) or "
            "expires history a new consumer generation expected to "
            "bootstrap from",
            fix=f"raise log.retention.ms to >= {interval}, lower the "
                "checkpoint interval, or disable time retention")


@config_rule("CLEANER_DISABLED_WITH_RETENTION", "warn",
             fix="set log.cleaner.enabled=true (the driver then runs "
                 "compaction + retention at log.cleaner.interval-ms "
                 "under the fenced cleaner lease), or schedule "
                 "explicit `log TOPIC_DIR --retain` passes")
def cleaner_disabled_with_retention(plan, config) -> Iterable[Finding]:
    """A retention policy with no executor: ``log.retention.ms`` /
    ``log.retention.bytes`` describe WHAT to drop, but nothing in the
    runtime drops it unless the background cleaner is enabled
    (``log.cleaner.enabled``) or an operator runs explicit
    maintenance passes. A topic configured this way grows without
    bound while its owner believes retention is active — the classic
    silently-ignored-config failure, surfaced at submit instead of at
    the disk-full incident. Fires only when the plan actually
    PRODUCES into a topic (a LogSink node): a consume-only job
    inherits the producer's maintenance regime."""
    from flink_tpu.config import LogOptions

    if bool(config.get(LogOptions.CLEANER_ENABLED)):
        return
    retention_ms = int(config.get(LogOptions.RETENTION_MS))
    retention_bytes = int(config.get(LogOptions.RETENTION_BYTES))
    if retention_ms <= 0 and retention_bytes <= 0:
        return
    if not _has_log_sink(plan):
        return
    configured = ", ".join(
        f"{k}={v}" for k, v in (("log.retention.ms", retention_ms),
                                ("log.retention.bytes", retention_bytes))
        if v > 0)
    yield _f(
        f"{configured} configured but log.cleaner.enabled=false: "
        "retention policy has NO executor — nothing in the runtime "
        "applies it, so the topic grows without bound unless explicit "
        "maintenance passes run out of band",
        fix="enable log.cleaner.enabled (leased background "
            "compaction + retention per producing topic), or drop the "
            "retention keys if out-of-band `log --retain` passes are "
            "the plan")


def _has_log_sink(plan) -> bool:
    from flink_tpu.log.connectors import LogSink

    if plan is None:
        # config-only analysis (analyze_config / `analyze --conf`):
        # no plan to inspect — retention keys alone signal log-tier
        # intent, so warn conservatively
        return True
    return any(n.kind == "sink" and isinstance(n.sink, LogSink)
               for n in plan.nodes.values())


@config_rule("LOG_PREFETCH_INVALID", "warn",
             fix="log.prefetch-segments >= 0, log.read-batch-records "
                 ">= 0, log.fsync-mode in {group, segment}; set "
                 "log.prefetch-segments=0 when auditing a savepoint "
                 "rewind")
def log_prefetch_invalid(plan, config) -> Iterable[Finding]:
    """A misconfigured perf-grade log read/write path: a negative
    prefetch depth or coalescing target would only fail at LogSource
    construction deep inside the job build, an unknown fsync-mode at
    the first stage — and prefetch combined with an EXPLICIT replay
    rewind (a configured restore path on a consumer-group job) makes
    a rewind audit's batch boundaries nondeterministic (the readahead
    re-reads rows past the frozen barrier; positions stay exact, but a
    side-by-side diff of delivered batches won't line up run to run)."""
    from flink_tpu.config import CheckpointingOptions, LogOptions

    prefetch = int(config.get(LogOptions.PREFETCH_SEGMENTS))
    batch_records = int(config.get(LogOptions.READ_BATCH_RECORDS))
    fsync_mode = str(config.get(LogOptions.FSYNC_MODE))
    if prefetch < 0:
        yield _f(
            f"log.prefetch-segments={prefetch} is negative: LogSource "
            "rejects it at construction, deep inside the job build — "
            "0 disables readahead, >= 1 sets the decode-ahead depth",
            fix="set log.prefetch-segments >= 0")
    if batch_records < 0:
        yield _f(
            f"log.read-batch-records={batch_records} is negative: "
            "LogSource rejects it at construction — 0 reads per "
            "on-disk block, >= 1 coalesces blocks to that many rows",
            fix="set log.read-batch-records >= 0")
    if fsync_mode not in ("group", "segment"):
        yield _f(
            f"log.fsync-mode={fsync_mode!r} is not a known mode: the "
            "sink rejects it at construction, deep inside the job "
            "build",
            fix="use 'group' (batched pre-marker fsync pass) or "
                "'segment' (legacy fsync-per-file)")
    restore = str(config.get(CheckpointingOptions.RESTORE) or "").strip()
    group = str(config.get(LogOptions.GROUP_NAME) or "").strip()
    if (prefetch > 0 and group and restore
            and restore not in ("", "latest")):
        yield _f(
            f"log.prefetch-segments={prefetch} with an explicit "
            f"replay rewind (execution.checkpointing.restore="
            f"{restore!r}) on consumer group {group!r}: the rewound "
            "position is authoritative and re-delivers rows below the "
            "group's committed offset, and readahead makes the "
            "re-delivered batch boundaries nondeterministic run to "
            "run — exactly-once is unaffected, but a rewind AUDIT "
            "(diffing delivered batches) should read inline",
            fix="set log.prefetch-segments=0 for the audit run, or "
                "drop the explicit restore path")


@config_rule("FAULT_POINT_UNKNOWN", "error",
             fix="match a faults.KNOWN_FAULT_POINTS entry")
def fault_point_unknown(plan, config) -> Iterable[Finding]:
    """A faults.inject rule whose point glob matches no registered
    fault point injects NOTHING — a chaos conf that silently does
    nothing is worse than no chaos at all."""
    from flink_tpu.faults import FAULT_INJECT, FAULT_SEED, FaultPlan
    from flink_tpu.faults import KNOWN_FAULT_POINTS

    spec = str(config.get(FAULT_INJECT) or "").strip()
    if not spec:
        return
    try:
        fplan = FaultPlan.from_spec(spec, seed=int(config.get(FAULT_SEED)))
    except ValueError as e:
        yield _f(f"faults.inject does not parse: {e}",
                 fix="grammar: 'point=kind [@prob] [xCOUNT] [+AFTER] "
                     "[~DELAY_MS]', rules ';'-separated")
        return
    for r in fplan.rules:
        if not any(fnmatch.fnmatchcase(p, r.point)
                   for p in KNOWN_FAULT_POINTS):
            close = difflib.get_close_matches(
                r.point, sorted(KNOWN_FAULT_POINTS), n=1)
            hint = (f"did you mean {close[0]!r}? " if close else "")
            yield _f(
                f"faults.inject rule {r.point!r} matches no registered "
                "fault point — it will never inject",
                fix=hint + "see flink_tpu.faults.KNOWN_FAULT_POINTS "
                    "for the registry")


@config_rule("CONFIG_KEY_UNKNOWN", "warn",
             fix="fix the typo or declare the ConfigOption")
def config_key_unknown(plan, config) -> Iterable[Finding]:
    """A set key outside the declared option grammar is almost always a
    typo — the job silently runs with the default of the key you meant."""
    from flink_tpu.config import all_options, is_declared_key

    load_option_grammar()
    known = sorted(all_options())
    for key in config.keys():
        if not is_declared_key(key):
            close = difflib.get_close_matches(key, known, n=1)
            yield _f(
                f"config key {key!r} is not in the declared option "
                "grammar — the job ignores it",
                fix=(f"did you mean {close[0]!r}?" if close else
                     "declare it as a ConfigOption (or under a dynamic "
                     "prefix, config.declare_dynamic_prefix)"))


@config_rule("HOST_PARALLELISM_INVALID", "warn",
             fix="set 1 <= host.parallelism <= os.cpu_count()")
def host_parallelism_invalid(plan, config) -> Iterable[Finding]:
    """host.parallelism outside [1, os.cpu_count()]: below 1 the driver
    cannot size the shared host pool and rejects the job at build;
    above the core count the workers contend for cores instead of
    scaling (the §9.4 contract sizes pools FROM os.cpu_count())."""
    from flink_tpu.config import HostOptions

    try:
        w = int(config.get(HostOptions.PARALLELISM))
    except (TypeError, ValueError):
        yield _f(
            "host.parallelism does not parse as an integer",
            fix="set an integer >= 1 (1 = serial path; default "
                "min(4, os.cpu_count()))")
        return
    ncpu = os.cpu_count() or 1
    if w < 1:
        yield _f(
            f"host.parallelism={w} is below 1 — the shared host worker "
            "pool cannot be sized and the driver rejects the job at "
            "build",
            fix="set host.parallelism >= 1 (1 = the exact serial path)")
    elif w > ncpu:
        yield _f(
            f"host.parallelism={w} exceeds os.cpu_count()={ncpu} — "
            "oversubscribed workers contend for cores instead of "
            "scaling the host operator paths",
            fix=f"set host.parallelism <= {ncpu} (default "
                f"min(4, os.cpu_count()) = {min(4, ncpu)})")


@config_rule("SESSION_QUOTA_INVALID", "error",
             fix="set 1 <= session.slots-per-job <= "
                 "session.runner-slots, and session.max-jobs >= 1")
def session_quota_invalid(plan, config) -> Iterable[Finding]:
    """A session-cluster quota the dispatcher can never satisfy: a
    slots-per-job or max-jobs or runner-slots below 1 (admission
    rejects the submission / the dispatcher refuses to start), or a
    per-job slot quota above one runner's slot capacity — no runner in
    the fleet could ever host the job, so it would be rejected at
    submit (runtime/session.py enforces the same bounds)."""
    from flink_tpu.config import SessionOptions

    def _get(opt, label):
        try:
            return int(config.get(opt)), None
        except (TypeError, ValueError):
            return None, _f(
                f"{label} does not parse as an integer",
                fix=f"set an integer >= 1 for {label}")

    spj, err = _get(SessionOptions.SLOTS_PER_JOB, "session.slots-per-job")
    if err is not None:
        yield err
        return
    rs, err = _get(SessionOptions.RUNNER_SLOTS, "session.runner-slots")
    if err is not None:
        yield err
        return
    mj, err = _get(SessionOptions.MAX_JOBS, "session.max-jobs")
    if err is not None:
        yield err
        return
    if spj < 1:
        yield _f(
            f"session.slots-per-job={spj} is below 1 — the dispatcher "
            "rejects the submission at admission",
            fix="set session.slots-per-job >= 1 (1 = the default "
                "single-slot share)")
    if mj < 1:
        yield _f(
            f"session.max-jobs={mj} is below 1 — the session cluster "
            "could never run a job and refuses to start",
            fix="set session.max-jobs >= 1")
    if rs < 1:
        yield _f(
            f"session.runner-slots={rs} is below 1 — runners would "
            "contribute no slot capacity and the cluster refuses to "
            "start",
            fix="set session.runner-slots >= 1")
    elif spj > rs:
        yield _f(
            f"session.slots-per-job={spj} exceeds "
            f"session.runner-slots={rs} — the quota is above every "
            "runner's slot capacity, so no fleet of any size could "
            "ever place the job (admission rejects it)",
            fix=f"lower session.slots-per-job to <= {rs}, or raise "
                "session.runner-slots")


@config_rule("SESSION_HA_UNSAFE", "warn",
             fix="set high-availability.dir to a shared directory and "
                 "run a standby contender (`session start --standby`)")
def session_ha_unsafe(plan, config) -> Iterable[Finding]:
    """A session cluster running CHECKPOINTING jobs without
    ``high-availability.dir``: every tenant's state is individually
    durable (checkpoints + transactional sinks survive a crash), but
    one dispatcher SIGKILL strands ALL of them — queued, running, and
    admitted-but-undeployed jobs evaporate with the in-memory registry
    even though each could have recovered. The durable session
    registry + standby takeover (runtime/session.py serve_session)
    exists exactly for this; a cluster that bothered to checkpoint
    should bother to survive its control plane."""
    from flink_tpu.config import (
        CheckpointingOptions,
        HighAvailabilityOptions,
    )

    quota_keys = ("session.runner-slots", "session.max-jobs",
                  "session.slots-per-job")
    present = [k for k in quota_keys if k in set(config.keys())]
    if not present:
        return  # no session-cluster intent in this config
    if int(config.get(CheckpointingOptions.INTERVAL)) <= 0:
        return  # nothing durable to strand: re-submission is recovery
    if str(config.get(HighAvailabilityOptions.HA_DIR)).strip():
        return
    yield _f(
        f"session-cluster config ({', '.join(present)}) runs "
        "checkpointing jobs with no high-availability.dir: a "
        "dispatcher crash strands every tenant's queued and running "
        "jobs even though their checkpoints would survive it — no "
        "durable session registry, no standby takeover, no leader "
        "epoch fencing",
        fix="set high-availability.dir to a directory every contender "
            "and runner shares, and start a hot standby with "
            "`session start --standby --ha-dir <dir>`")


@config_rule("DCN_OVERLAP_UNSAFE", "warn",
             fix="leave cluster.dcn-overlap-drain true (the default), "
                 "or disable checkpointing / overlap")
def dcn_overlap_unsafe(plan, config) -> Iterable[Finding]:
    """Step-overlapped cross-host exchange with checkpointing but the
    barrier drain DISABLED: the snapshot's source positions include
    the one in-flight exchange step, whose records are still on the
    wire — a restore from that checkpoint skips past them (at-most-
    once for that step). The drain exists exactly so the cut covers
    every routed record; turning it off is a loss-tolerant perf trade
    that must be a visible decision, not a silent config."""
    from flink_tpu.config import CheckpointingOptions, ClusterOptions

    if int(config.get(ClusterOptions.NUM_PROCESSES)) <= 1:
        return  # no cross-host exchange in this job
    if int(config.get(CheckpointingOptions.INTERVAL)) <= 0:
        return  # nothing snapshots: nothing to miss the cut
    if not bool(config.get(ClusterOptions.DCN_OVERLAP)):
        return  # lockstep loop: the barrier IS the dispatch
    if bool(config.get(ClusterOptions.DCN_OVERLAP_DRAIN)):
        return  # drained at the barrier: the cut is complete
    yield _f(
        "cluster.dcn-overlap is on with checkpointing but "
        "cluster.dcn-overlap-drain is false: the in-flight overlapped "
        "exchange step is NOT drained at the checkpoint barrier, so "
        "its records are in the snapshot's source positions but in "
        "nobody's state — a restore from that checkpoint loses them "
        "(at-most-once for that step)",
        fix="leave cluster.dcn-overlap-drain true (the default; one "
            "extra consume per checkpoint), or disable "
            "cluster.dcn-overlap / checkpointing if the pipeline "
            "tolerates loss")


@config_rule("SUBBATCH_INVALID", "error",
             fix="pick a divisor of pipeline.microbatch-size")
def subbatch_invalid(plan, config) -> Iterable[Finding]:
    """pipeline.sub-batches misconfigurations the driver would reject
    at build (or that silently defeat the feature): a count below 1, a
    count that does not divide pipeline.microbatch-size (sub-batches
    are EQUAL slices of the logical batch — ragged configured slices
    would compile extra kernel buckets and skew the fire cadence), or
    an explicit emit deferral at logical-batch scale (>= the 100ms
    accelerator deferral) that re-serializes fire visibility to
    full-batch cadence — the emit-defer floor sub-batching exists to
    get under."""
    from flink_tpu.config import PipelineOptions

    try:
        k = int(config.get(PipelineOptions.SUB_BATCHES))
    except (TypeError, ValueError):
        yield _f(
            "pipeline.sub-batches does not parse as an integer",
            fix="set an integer >= 1 that divides "
                "pipeline.microbatch-size (1 = no sub-batching)")
        return
    if k < 1:
        yield _f(
            f"pipeline.sub-batches={k} is below 1 — the driver rejects "
            "the job at build",
            fix="set pipeline.sub-batches >= 1 (1 = the exact "
                "single-dispatch path)")
        return
    mb = int(config.get(PipelineOptions.MICROBATCH_SIZE))
    if mb % k:
        yield _f(
            f"pipeline.sub-batches={k} does not divide "
            f"pipeline.microbatch-size={mb} — sub-batches are equal "
            "slices of the logical batch; the driver rejects this at "
            "build",
            fix=f"pick a divisor of {mb} (powers of two divide the "
                "default sizes), or adjust pipeline.microbatch-size")
    if k > 1:
        defer = int(config.get(PipelineOptions.EMIT_DEFER_MS))
        if defer >= 100:
            yield _f(
                f"pipeline.emit-defer={defer}ms with "
                f"pipeline.sub-batches={k} violates the emit-defer "
                "floor: the drain defers each fired sub-batch past the "
                "sub-batch cadence, re-serializing emit visibility to "
                "logical-batch latency — the exact tax sub-batching "
                "removes",
                fix="leave pipeline.emit-defer on auto (-1, 10ms on "
                    "accelerators) or set it well below the sub-batch "
                    "wall time")


@config_rule("READINESS_INVALID", "error",
             fix="pipeline.readiness is 'piggyback' or 'probe'")
def readiness_invalid(plan, config) -> Iterable[Finding]:
    """An unknown pipeline.readiness value: the driver rejects the job
    at build (inside Driver._build_ops), so the default
    analysis.fail-on=error gate must block it at SUBMIT — the
    SUBBATCH_INVALID discipline for build-rejected config."""
    from flink_tpu.config import PipelineOptions

    readiness = str(config.get(PipelineOptions.READINESS)).strip().lower()
    if readiness not in ("piggyback", "probe"):
        yield _f(
            f"pipeline.readiness={readiness!r} is not a known mode: "
            "the driver rejects the job at build",
            fix="use 'piggyback' (throttle consumes an announced "
                "per-step token — no is_ready round trips) or 'probe' "
                "(legacy is_ready spin, zero per-step d2h traffic)")


@config_rule("FIRE_GATE_INVALID", "warn",
             fix="leave pipeline.fire-gate true (the default) under "
                 "sub-batching")
def fire_gate_invalid(plan, config) -> Iterable[Finding]:
    """Fire-gating forced OFF under a config that needs it (PROFILE.md
    §12): pipeline.sub-batches > 1 pays the fire/top-n select sort on
    EVERY sub-batch dispatch whether or not any window fires — exactly
    the §8.6 throughput-vs-K tax the gate removes. Warn, not error:
    gate-off is the legitimate A/B measurement axis."""
    from flink_tpu.config import PipelineOptions

    try:
        gate = bool(config.get(PipelineOptions.FIRE_GATE))
        k = int(config.get(PipelineOptions.SUB_BATCHES))
    except (TypeError, ValueError):
        return  # SUBBATCH_INVALID owns the parse failure
    if not gate and k > 1:
        yield _f(
            f"pipeline.fire-gate=false with pipeline.sub-batches={k}: "
            "every sub-batch dispatch pays the full fire/top-n select "
            "subgraph (one dominant sort) whether or not any window "
            "can fire — K dispatches per logical batch pay it K times, "
            "the measured §8.6 throughput tax that made sub-batching "
            "trade throughput for p99",
            fix="leave pipeline.fire-gate true (committed output is "
                "byte-identical; false exists as the A/B measurement "
                "axis), or run sub-batches=1 if the gate must stay off")


@config_rule("CHECKPOINT_IN_BATCH", "error",
             fix="drop checkpointing config or run in streaming mode")
def checkpoint_in_batch(plan, config) -> Iterable[Finding]:
    """Bounded-mode recovery is re-execution: nothing checkpoints, so a
    checkpoint interval or an explicit restore path is a config
    contradiction (the driver rejects it at run; this catches it at
    submit)."""
    from flink_tpu.config import CheckpointingOptions

    if _runtime_mode(config) != "batch":
        return
    if config.get(CheckpointingOptions.INTERVAL) > 0:
        yield _f(
            "execution.checkpointing.interval is incompatible with "
            "execution.runtime-mode=batch (bounded-mode recovery is "
            "re-execution; 2PC sinks commit once at end of input)",
            fix="drop the interval, or run in streaming mode")
    restore = str(config.get(CheckpointingOptions.RESTORE)).strip()
    if restore and restore != "latest":
        # restore=latest is injected by supervisor redeploys and the
        # driver degrades it to a fresh run; an explicit path cannot work
        yield _f(
            f"execution.checkpointing.restore={restore!r} is "
            "incompatible with execution.runtime-mode=batch (nothing "
            "checkpoints in batch mode — re-run the job)",
            fix="drop the restore path, or run in streaming mode")


@config_rule("RESCALE_INVALID", "error",
             fix="make the rescale.* config self-consistent")
def rescale_invalid(plan, config) -> Iterable[Finding]:
    """Rescale config that can never work (error) or that will thrash
    (warn), caught at submit instead of at the first arm:

    - reactive mode without checkpointing is an ERROR: the handshake is
      savepoint-based (stop-with-savepoint → key-group repartition →
      redeploy), so the controller would arm rescales whose savepoints
      the runner rejects, forever.
    - device bounds that violate the key-group discipline are an
      ERROR: the per-process shard share must stay divisible by every
      width the controller may pick, and an empty [min, max] range can
      pick none.
    - an inverted pressure band (low >= high) is an ERROR: the
      hysteresis dead zone is empty, so one sample can sit on both
      sides and the controller flaps by construction.

    The thrash-but-legal shapes warn instead (RESCALE_COOLDOWN_THRASH
    below)."""
    from flink_tpu.config import CheckpointingOptions, RescaleOptions

    mode = str(config.get(RescaleOptions.MODE)).strip().lower()
    if mode not in ("off", "reactive"):
        yield _f(
            f"rescale.mode={mode!r} is not a known mode",
            fix="use 'off' (manual RPC/CLI only) or 'reactive'")
        return
    if mode != "reactive":
        return
    interval = int(config.get(CheckpointingOptions.INTERVAL))
    if interval <= 0:
        yield _f(
            "rescale.mode=reactive without checkpointing: the rescale "
            "handshake is savepoint-based, so every controller-armed "
            "rescale would dispatch a stop-with-savepoint the runner "
            "rejects (no checkpoint storage) and disarm — an arm/"
            "disarm loop that never rescales",
            fix="set execution.checkpointing.interval (and .dir), or "
                "rescale.mode=off")
    hi = float(config.get(RescaleOptions.TARGET_PRESSURE_HIGH))
    lo = float(config.get(RescaleOptions.TARGET_PRESSURE_LOW))
    if lo >= hi:
        yield _f(
            f"rescale.target-pressure-low={lo:g} >= "
            f"rescale.target-pressure-high={hi:g}: the hysteresis dead "
            "zone is empty, so the controller classifies one pressure "
            "sample as both scale-out and scale-in and flaps",
            fix="keep low strictly below high (defaults 20/70)")
    try:
        shards = int(config.get_raw("state.num-key-shards", 128) or 128)
    except (TypeError, ValueError):
        shards = 128
    nproc = max(1, int(config.get_raw("cluster.num-processes", 1) or 1))
    share = shards // nproc if shards % nproc == 0 else 0
    mn = int(config.get(RescaleOptions.MIN_DEVICES))
    mx = int(config.get(RescaleOptions.MAX_DEVICES))
    if mn < 1:
        yield _f(
            f"rescale.min-devices={mn} is below 1",
            fix="set rescale.min-devices >= 1")
    elif mx and mx < mn:
        yield _f(
            f"rescale.max-devices={mx} < rescale.min-devices={mn}: "
            "the legal width range is empty — the controller can "
            "never pick a target",
            fix="widen the range (0 max = current fleet capacity)")
    if share:
        for opt, v in (("rescale.min-devices", mn),
                       ("rescale.max-devices", mx)):
            if v > 0 and share % v != 0:
                yield _f(
                    f"{opt}={v} does not divide the per-process shard "
                    f"share ({shards} shards / {nproc} processes = "
                    f"{share}): the key-group discipline (contiguous "
                    "equal ranges per device) is unsatisfiable at that "
                    "width, so the controller would clamp against a "
                    "bound it can never reach",
                    fix=f"pick a divisor of {share} (powers of two "
                        "divide the default 128)")


@config_rule("RESCALE_COOLDOWN_THRASH", "warn",
             fix="keep rescale.cooldown above "
                 "execution.checkpointing.interval")
def rescale_cooldown_thrash(plan, config) -> Iterable[Finding]:
    """A reactive rescale cooldown below the checkpoint interval: the
    controller can re-arm before the first post-rescale checkpoint
    publishes, so every rescale restores from the previous rescale's
    savepoint floor instead of fresh progress — legal (exactly-once
    holds), but under sustained pressure the job spends its life
    savepointing and restoring rather than processing. Warn, not
    error: a one-shot burst workload may want an aggressive cooldown
    and accept the tax."""
    from flink_tpu.config import CheckpointingOptions, RescaleOptions

    mode = str(config.get(RescaleOptions.MODE)).strip().lower()
    if mode != "reactive":
        return
    interval = int(config.get(CheckpointingOptions.INTERVAL))
    if interval <= 0:
        return  # RESCALE_INVALID owns the no-checkpointing error
    cooldown = int(config.get(RescaleOptions.COOLDOWN))
    if cooldown < interval:
        yield _f(
            f"rescale.cooldown={cooldown}ms is below "
            f"execution.checkpointing.interval={interval}ms: the "
            "controller can re-arm before the first post-rescale "
            "checkpoint publishes, so back-to-back rescales keep "
            "restoring the previous savepoint floor — the job "
            "thrashes between savepoint and restore under sustained "
            "pressure",
            fix=f"set rescale.cooldown >= {interval}ms (and ideally "
                "several checkpoint intervals)")


@config_rule("STATE_BUDGET_INVALID", "error",
             fix="make the state.* backend config self-consistent")
def state_budget_invalid(plan, config) -> Iterable[Finding]:
    """State-backend config that can never work (error) or that does
    nothing (warn), caught at submit:

    - an unknown ``state.backend`` is an ERROR: the driver rejects the
      job at build (runtime/driver.py validates against hbm/spill/lsm).
    - an lsm memory budget below ``state.lsm.run-floor-bytes`` is an
      ERROR: the delta would seal a degenerate run on nearly every
      batch, turning every absorb into an fsync — the disk tier
      becomes a write amplifier instead of a spill tier.
    - ``state.lsm.compact-min-runs`` below 2 is an ERROR: a compaction
      of fewer than two runs merges nothing, and the store would arm
      it after every seal.

    The does-nothing shape warns instead (STATE_BUDGET_IGNORED
    below)."""
    from flink_tpu.config import StateOptions

    backend = str(config.get(StateOptions.BACKEND)).strip().lower()
    if backend not in ("hbm", "spill", "lsm"):
        yield _f(
            f"state.backend={backend!r} is not a known backend — the "
            "driver rejects the job at build",
            fix="use 'hbm' (dense device panes), 'spill' (RAM host "
                "offload) or 'lsm' (disk tier)")
        return
    if backend != "lsm":
        return
    try:
        budget = int(config.get(StateOptions.MEMORY_BUDGET_BYTES))
        floor = int(config.get(StateOptions.LSM_RUN_FLOOR_BYTES))
    except (TypeError, ValueError):
        yield _f(
            "state.memory-budget-bytes / state.lsm.run-floor-bytes do "
            "not parse as integers",
            fix="set byte counts (default budget 64 MiB, floor 64 KiB)")
        return
    if budget < floor:
        yield _f(
            f"state.memory-budget-bytes={budget} is below "
            f"state.lsm.run-floor-bytes={floor}: the lsm delta would "
            "seal a degenerate run on nearly every batch — every "
            "absorb becomes an fsync and the disk tier amplifies "
            "writes instead of spilling them",
            fix=f"raise the budget to >= {floor} bytes (or lower the "
                "floor if tiny runs are intended, e.g. crash tests)")
    try:
        cmin = int(config.get(StateOptions.LSM_COMPACT_MIN_RUNS))
    except (TypeError, ValueError):
        yield _f(
            "state.lsm.compact-min-runs does not parse as an integer",
            fix="set an integer >= 2 (default 4)")
        return
    if cmin < 2:
        yield _f(
            f"state.lsm.compact-min-runs={cmin} is below 2 — a "
            "compaction of fewer than two runs merges nothing, and "
            "the store would arm one after every seal",
            fix="set state.lsm.compact-min-runs >= 2 (default 4)")


@config_rule("STATE_BUDGET_IGNORED", "warn",
             fix="set state.backend=lsm, or drop the key")
def state_budget_ignored(plan, config) -> Iterable[Finding]:
    """``state.memory-budget-bytes`` explicitly set while the backend
    is not 'lsm': hbm/spill hold all state resident and ignore the
    key, so the bound the operator thinks they configured does not
    exist — the job OOMs exactly as if the key were absent."""
    from flink_tpu.config import StateOptions

    backend = str(config.get(StateOptions.BACKEND)).strip().lower()
    if backend == "lsm" or backend not in ("hbm", "spill"):
        return  # STATE_BUDGET_INVALID owns the unknown-backend error
    if "state.memory-budget-bytes" in config.keys():
        yield _f(
            "state.memory-budget-bytes is set but "
            f"state.backend={backend!r} ignores it — only the 'lsm' "
            "backend bounds its in-memory delta; this job holds all "
            "state resident",
            fix="set state.backend=lsm to enable the disk tier, or "
                "drop the key")


def load_option_grammar() -> None:
    """Import every module that declares ConfigOptions so the registry
    is complete before a key-validity check (options register at module
    import; a job that never touches metrics would otherwise see
    ``metrics.port`` as unknown)."""
    import flink_tpu.config  # noqa: F401
    import flink_tpu.faults  # noqa: F401
    import flink_tpu.obs.metrics  # noqa: F401
