"""Analysis core — the Finding record and the rule engine.

A rule is a function ``(plan, config) -> Iterable[Finding]`` registered
with :func:`plan_rule` (needs a lowered plan) or :func:`config_rule`
(configuration alone — runnable without compiling a pipeline). The
engine just runs every registered rule and concatenates findings;
severity and rule id live on the registration so the catalog is
greppable in one place (``plan_rules.py``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warn")
# severity sort weight: errors first in every report
_SEV_ORDER = {"error": 0, "warn": 1}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured analysis result (rule id, severity, where, what,
    how to fix). ``node``/``node_name`` locate plan findings;
    ``file``/``line`` locate AST-lint findings."""

    rule: str
    severity: str
    message: str
    fix: str = ""
    node: Optional[int] = None
    node_name: str = ""
    file: str = ""
    line: int = 0

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"finding severity must be one of {SEVERITIES}, "
                f"got {self.severity!r}")

    def where(self) -> str:
        if self.file:
            return f"{self.file}:{self.line}"
        if self.node is not None:
            name = f" ({self.node_name})" if self.node_name else ""
            return f"node {self.node}{name}"
        return "config"

    def render(self) -> str:
        hint = f"\n    fix: {self.fix}" if self.fix else ""
        return (f"[{self.severity}] {self.rule} at {self.where()}: "
                f"{self.message}{hint}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def render_findings(findings: Iterable[Finding]) -> str:
    fs = sorted(findings, key=lambda f: (_SEV_ORDER[f.severity], f.rule))
    if not fs:
        return "no findings"
    return "\n".join(f.render() for f in fs)


class AnalysisError(ValueError):
    """Raised at submit when findings reach the ``analysis.fail-on``
    threshold. Subclasses ValueError: analysis failures are config/graph
    validation errors, same family as the compiler's own rejections."""

    def __init__(self, findings: List[Finding], threshold: str) -> None:
        self.findings = list(findings)
        self.threshold = threshold
        super().__init__(
            f"plan analysis found {len(self.findings)} blocking "
            f"finding(s) (analysis.fail-on={threshold}; set "
            "analysis.fail-on: off to skip):\n"
            + render_findings(self.findings))


# -- rule registry ----------------------------------------------------------

RuleFn = Callable[[Any, Any], Iterable[Finding]]

# the documented analysis planes (RULES.md groups by these): "plan" =
# linear walks over the lowered plan, "config" = Configuration alone,
# "dataflow" = rules over the propagated lattices (analysis/dataflow.py);
# the repo AST lints are a sibling "pylint" plane (pylints.LINT_CATALOG)
PLANES = ("plan", "config", "dataflow")


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    """One registered rule's catalog entry — what RULES.md renders and
    the coverage test parametrizes over."""

    rule_id: str
    severity: str
    plane: str
    needs_plan: bool
    description: str  # first sentence of the rule's docstring
    fix: str          # catalog-level fix hint (findings carry specifics)
    fn: RuleFn


_RULES: List[RuleInfo] = []


def _doc_summary(fn: RuleFn) -> str:
    """First sentence of the rule docstring, whitespace-collapsed —
    the one-line description RULES.md publishes."""
    doc = " ".join((fn.__doc__ or "").split())
    for stop in (". ", ".\n"):
        if stop in doc:
            return doc.split(stop, 1)[0] + "."
    return doc


def _register(rule_id: str, severity: str, needs_plan: bool, plane: str,
              fix: str):
    if severity not in SEVERITIES:
        raise ValueError(f"bad severity {severity!r} for rule {rule_id}")
    if plane not in PLANES:
        raise ValueError(f"bad plane {plane!r} for rule {rule_id}")

    def deco(fn: RuleFn) -> RuleFn:
        _RULES.append(RuleInfo(rule_id, severity, plane, needs_plan,
                               _doc_summary(fn), fix, fn))
        fn.rule_id = rule_id
        fn.severity = severity
        return fn

    return deco


def plan_rule(rule_id: str, severity: str, plane: str = "plan",
              fix: str = ""):
    """Register a rule that needs a lowered ExecutionPlan."""
    return _register(rule_id, severity, needs_plan=True, plane=plane,
                     fix=fix)


def config_rule(rule_id: str, severity: str, fix: str = ""):
    """Register a rule over the Configuration alone."""
    return _register(rule_id, severity, needs_plan=False, plane="config",
                     fix=fix)


def rule_catalog() -> List[Tuple[str, str]]:
    """(rule_id, severity) of every registered rule — docs and the
    coverage test read this so no rule can ship untested."""
    _load_rules()
    return [(r.rule_id, r.severity) for r in _RULES]


def rule_catalog_full() -> List[RuleInfo]:
    """Every registered rule with plane/description/fix metadata — the
    RULES.md generation surface (analysis/docs.py)."""
    _load_rules()
    return list(_RULES)


def _load_rules() -> None:
    # rule definitions live in plan_rules.py + dataflow.py; importing
    # them populates the registry (idempotent — the registry appends
    # only at module init)
    from flink_tpu.analysis import dataflow, plan_rules  # noqa: F401


def analyze(plan: Any, config: Any, *,
            eval_chains: bool = True) -> List[Finding]:
    """Run every rule over (plan, config). ``plan`` may be None to run
    configuration rules alone (the conf-only CLI path).

    ``eval_chains`` gates the dataflow plane's abstract evaluation of
    user chain functions on empty typed batches (schema inference
    through map/filter/flat_map). The explicit surfaces — ``env
    .analyze()`` and ``python -m flink_tpu analyze`` — evaluate them;
    the DRIVER's automatic submit-time pass does not (a user fn with
    observable side effects must never see a phantom empty batch just
    because the job was submitted), so submit-time schema facts stop at
    the first opaque chain."""
    _load_rules()
    from flink_tpu.analysis import dataflow

    out: List[Finding] = []
    with dataflow.chain_eval_mode(eval_chains):
        for info in _RULES:
            if info.needs_plan and plan is None:
                continue
            for f in info.fn(plan, config):
                # the registration owns id+severity; rules fill the rest
                out.append(dataclasses.replace(
                    f, rule=info.rule_id, severity=info.severity))
    out.sort(key=finding_sort_key)
    return out


def finding_sort_key(f: Finding):
    """Deterministic report order: severity, rule, then node index with
    config-level findings (node=None) explicitly LAST — ``f.node or 0``
    used to conflate node 0 with None, so a rule firing on both gave an
    input-order-dependent interleave (regression-tested)."""
    return (_SEV_ORDER[f.severity], f.rule, f.node is None,
            f.node if f.node is not None else 0, f.file, f.line)


def analyze_config(config: Any) -> List[Finding]:
    return analyze(None, config)


def blocking(findings: Iterable[Finding], fail_on: str) -> List[Finding]:
    """The subset of findings that fails the job under
    ``analysis.fail-on=fail_on`` ('error' blocks errors only, 'warn'
    blocks both, 'off' blocks nothing)."""
    fail_on = (fail_on or "error").strip().lower()
    if fail_on == "off":
        return []
    if fail_on == "warn":
        return list(findings)
    if fail_on != "error":
        raise ValueError(
            f"analysis.fail-on must be error|warn|off, got {fail_on!r}")
    return [f for f in findings if f.severity == "error"]
