"""Analysis core — the Finding record and the rule engine.

A rule is a function ``(plan, config) -> Iterable[Finding]`` registered
with :func:`plan_rule` (needs a lowered plan) or :func:`config_rule`
(configuration alone — runnable without compiling a pipeline). The
engine just runs every registered rule and concatenates findings;
severity and rule id live on the registration so the catalog is
greppable in one place (``plan_rules.py``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warn")
# severity sort weight: errors first in every report
_SEV_ORDER = {"error": 0, "warn": 1}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured analysis result (rule id, severity, where, what,
    how to fix). ``node``/``node_name`` locate plan findings;
    ``file``/``line`` locate AST-lint findings."""

    rule: str
    severity: str
    message: str
    fix: str = ""
    node: Optional[int] = None
    node_name: str = ""
    file: str = ""
    line: int = 0

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"finding severity must be one of {SEVERITIES}, "
                f"got {self.severity!r}")

    def where(self) -> str:
        if self.file:
            return f"{self.file}:{self.line}"
        if self.node is not None:
            name = f" ({self.node_name})" if self.node_name else ""
            return f"node {self.node}{name}"
        return "config"

    def render(self) -> str:
        hint = f"\n    fix: {self.fix}" if self.fix else ""
        return (f"[{self.severity}] {self.rule} at {self.where()}: "
                f"{self.message}{hint}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def render_findings(findings: Iterable[Finding]) -> str:
    fs = sorted(findings, key=lambda f: (_SEV_ORDER[f.severity], f.rule))
    if not fs:
        return "no findings"
    return "\n".join(f.render() for f in fs)


class AnalysisError(ValueError):
    """Raised at submit when findings reach the ``analysis.fail-on``
    threshold. Subclasses ValueError: analysis failures are config/graph
    validation errors, same family as the compiler's own rejections."""

    def __init__(self, findings: List[Finding], threshold: str) -> None:
        self.findings = list(findings)
        self.threshold = threshold
        super().__init__(
            f"plan analysis found {len(self.findings)} blocking "
            f"finding(s) (analysis.fail-on={threshold}; set "
            "analysis.fail-on: off to skip):\n"
            + render_findings(self.findings))


# -- rule registry ----------------------------------------------------------

RuleFn = Callable[[Any, Any], Iterable[Finding]]
# (rule_id, severity, needs_plan, fn)
_RULES: List[Tuple[str, str, bool, RuleFn]] = []


def _register(rule_id: str, severity: str, needs_plan: bool):
    if severity not in SEVERITIES:
        raise ValueError(f"bad severity {severity!r} for rule {rule_id}")

    def deco(fn: RuleFn) -> RuleFn:
        _RULES.append((rule_id, severity, needs_plan, fn))
        fn.rule_id = rule_id
        fn.severity = severity
        return fn

    return deco


def plan_rule(rule_id: str, severity: str):
    """Register a rule that needs a lowered ExecutionPlan."""
    return _register(rule_id, severity, needs_plan=True)


def config_rule(rule_id: str, severity: str):
    """Register a rule over the Configuration alone."""
    return _register(rule_id, severity, needs_plan=False)


def rule_catalog() -> List[Tuple[str, str]]:
    """(rule_id, severity) of every registered rule — docs and the
    coverage test read this so no rule can ship untested."""
    _load_rules()
    return [(rid, sev) for rid, sev, _, _ in _RULES]


def _load_rules() -> None:
    # rule definitions live in plan_rules.py; importing it populates the
    # registry (idempotent — the registry appends only at module init)
    from flink_tpu.analysis import plan_rules  # noqa: F401


def analyze(plan: Any, config: Any) -> List[Finding]:
    """Run every rule over (plan, config). ``plan`` may be None to run
    configuration rules alone (the conf-only CLI path)."""
    _load_rules()
    out: List[Finding] = []
    for rule_id, severity, needs_plan, fn in _RULES:
        if needs_plan and plan is None:
            continue
        for f in fn(plan, config):
            # the registration owns id+severity; rules fill the rest
            out.append(dataclasses.replace(
                f, rule=rule_id, severity=severity))
    out.sort(key=lambda f: (_SEV_ORDER[f.severity], f.rule, f.node or 0,
                            f.file, f.line))
    return out


def analyze_config(config: Any) -> List[Finding]:
    return analyze(None, config)


def blocking(findings: Iterable[Finding], fail_on: str) -> List[Finding]:
    """The subset of findings that fails the job under
    ``analysis.fail-on=fail_on`` ('error' blocks errors only, 'warn'
    blocks both, 'off' blocks nothing)."""
    fail_on = (fail_on or "error").strip().lower()
    if fail_on == "off":
        return []
    if fail_on == "warn":
        return list(findings)
    if fail_on != "error":
        raise ValueError(
            f"analysis.fail-on must be error|warn|off, got {fail_on!r}")
    return [f for f in findings if f.severity == "error"]
