"""Repo AST lints — pure-stdlib ``ast`` pass over the codebase itself.

The runtime's correctness leans on conventions no unit test can see
whole: jit kernels must stay trace-pure (PROFILE §8.1's design rules
exist because host round-trips inside kernels silently retrace or
pin stale values), ``faults.fire`` literals must match the registry in
``faults.py`` (a drifted literal = a chaos plan that injects nothing),
and config/metric name literals must stay inside their declared
grammars (a typo'd key silently runs the default). Each lint is one
linear AST walk; `python -m flink_tpu lint` and the tier-1 dogfood
gate (tests/test_analysis.py) keep the shipped tree at zero findings.

Rule catalog:

- ``TRACER_HOST_CALL`` (error): ``float()/int()/bool()``,
  ``np.asarray()/np.array()``, ``.item()/.tolist()`` applied to a value
  derived from a traced parameter inside a directly-jitted kernel —
  a host materialization that breaks tracing (ConcretizationTypeError
  at best, a silently-stale constant at worst).
- ``TRACER_BRANCH`` (error): Python ``if``/``while``/ternary (or
  ``range()`` iteration) on a value derived from a traced parameter
  inside a jitted kernel — control flow must go through ``lax.cond`` /
  ``jnp.where`` / masking.
- ``FAULT_POINT_DRIFT`` (error): a ``faults.fire("...")`` literal not
  in ``faults.KNOWN_FAULT_POINTS``.
- ``CONFIG_KEY_DRIFT`` (error): a string key passed to
  ``.get_raw()`` / ``Configuration({...})`` that is outside the
  declared option grammar.
- ``CONFIG_OPTION_DUP`` (error): one option key declared by two
  ``ConfigOption``/``duration_option`` literals — last registration
  silently wins.
- ``METRIC_NAME_INVALID`` (warn): a metric/group name literal outside
  the ``[a-z0-9_]`` snake-case grammar every dashboard keys on.

Honest scope (linear, syntactic): "derived from a traced parameter"
is one assignment hop inside the kernel body — no fixpoint, no
cross-function taint, no aliasing. Values reached only through static
attributes (``.shape``/``.ndim``/``.dtype``/``.size``), ``len()``,
``is None`` / ``in`` tests are NOT tainted (those are static under
tracing). Only functions jitted DIRECTLY (``@jit`` decorators or
``jax.jit(f)`` / ``jax.jit(shard_map(f, ...))`` on a local def) are
kernels: a helper merely *called* from a kernel may legitimately
receive concrete Python values, so it is out of scope.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from flink_tpu.analysis.core import Finding

LINT_RULES: Tuple[Tuple[str, str], ...] = (
    ("TRACER_HOST_CALL", "error"),
    ("TRACER_BRANCH", "error"),
    ("FAULT_POINT_DRIFT", "error"),
    ("CONFIG_KEY_DRIFT", "error"),
    ("CONFIG_OPTION_DUP", "error"),
    ("METRIC_NAME_INVALID", "warn"),
)
_SEV = dict(LINT_RULES)

_METRIC_KINDS = ("counter", "gauge", "meter", "histogram")
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)*$")
# attribute reads that are STATIC under tracing — a name reached only
# through these never carries the tracer into host code
_STATIC_ATTRS = frozenset(("shape", "ndim", "dtype", "size"))
_HOST_CONVERSIONS = frozenset(("float", "int", "bool"))
_HOST_METHODS = frozenset(("item", "tolist"))
_NP_MATERIALIZERS = frozenset(("asarray", "array"))


def _finding(rule: str, message: str, file: str, line: int,
             fix: str = "") -> Finding:
    return Finding(rule=rule, severity=_SEV[rule], message=message,
                   fix=fix, file=file, line=line)


# -- jit-kernel discovery ---------------------------------------------------

@dataclasses.dataclass
class _Kernel:
    fn: ast.AST                  # FunctionDef / AsyncFunctionDef / Lambda
    static_names: Set[str]


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` (any attribute path ending in .jit)."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return False


def _static_names(jit_call: Optional[ast.Call],
                  fn: ast.AST) -> Set[str]:
    """Param names excluded from tracing via static_argnums/names."""
    out: Set[str] = set()
    if jit_call is None:
        return out
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        out.add(params[c.value])
        elif kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


def _collect_kernels(tree: ast.Module) -> List[_Kernel]:
    """Functions DIRECTLY jitted in this file: decorator forms
    (``@jit``, ``@jax.jit``, ``@partial(jax.jit, ...)``,
    ``@jax.jit(...)`` with kwargs) and call forms (``jax.jit(f)``,
    ``jax.jit(shard_map(f, ...))`` where ``f`` is a local def)."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    kernels: List[_Kernel] = []
    seen: Set[int] = set()

    def add(fn: ast.AST, jit_call: Optional[ast.Call]) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        kernels.append(_Kernel(fn, _static_names(jit_call, fn)))

    for node in ast.walk(tree):
        # decorator forms
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    add(node, None)
                elif isinstance(dec, ast.Call):
                    if _is_jit_expr(dec.func):
                        add(node, dec)
                    elif (isinstance(dec.func, (ast.Name, ast.Attribute))
                          and (dec.func.attr if isinstance(
                              dec.func, ast.Attribute) else dec.func.id)
                          == "partial"
                          and dec.args and _is_jit_expr(dec.args[0])):
                        add(node, dec)
        # call forms: jax.jit(f) / jax.jit(shard_map(f, ...))
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            if not node.args:
                continue
            target = node.args[0]
            if (isinstance(target, ast.Call)
                    and isinstance(target.func, (ast.Name, ast.Attribute))
                    and (target.func.attr if isinstance(
                        target.func, ast.Attribute) else target.func.id)
                    == "shard_map" and target.args):
                target = target.args[0]
            if isinstance(target, ast.Name):
                for fn in defs_by_name.get(target.id, ()):
                    add(fn, node)
            elif isinstance(target, ast.Lambda):
                add(target, node)
    return kernels


# -- taint walk over one kernel body ----------------------------------------

class _TaintVisitor(ast.NodeVisitor):
    """One in-order pass over a kernel body. ``tainted`` starts as the
    traced parameter set; a single assignment hop propagates it. The
    visitor flags host conversions and Python control flow on tainted
    expressions."""

    def __init__(self, file: str, kernel_name: str,
                 tainted: Set[str]) -> None:
        self.file = file
        self.kernel = kernel_name
        self.tainted = set(tainted)
        self.findings: List[Finding] = []

    # -- taint test -------------------------------------------------------
    def _expr_tainted(self, node: ast.AST) -> bool:
        """Does this expression carry a traced value into host code?
        Names under static attributes / len() / `is`/`in` tests don't."""
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "len":
                return False  # len() of arrays/dicts is static
            if isinstance(fn, ast.Name) and fn.id == "isinstance":
                return False
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops):
            # `x is None` / `"col" in data` are static under tracing
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        return any(self._expr_tainted(c) for c in ast.iter_child_nodes(node))

    # -- taint propagation (one hop, source order) ------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if self._expr_tainted(node.value):
            self.tainted.update(names)
        else:
            self.tainted.difference_update(names)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if (isinstance(node.target, ast.Name)
                and self._expr_tainted(node.value)):
            self.tainted.add(node.target.id)

    # -- flagged sites ----------------------------------------------------
    def _flag(self, rule: str, line: int, what: str, fix: str) -> None:
        self.findings.append(_finding(
            rule, f"{what} inside jit kernel {self.kernel!r} — host "
            "round-trips on traced values retrace or pin stale "
            "constants", self.file, line, fix=fix))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Name) and fn.id in _HOST_CONVERSIONS
                and node.args and self._expr_tainted(node.args[0])):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f"{fn.id}() on a traced value",
                       "keep it on device (jnp.astype/where) or hoist "
                       "the conversion out of the kernel")
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _NP_MATERIALIZERS
              and isinstance(fn.value, ast.Name)
              and fn.value.id in ("np", "numpy")
              and node.args and self._expr_tainted(node.args[0])):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f"np.{fn.attr}() on a traced value",
                       "use jnp inside kernels; numpy materializes on "
                       "the host")
        elif (isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS
              and self._expr_tainted(fn.value)):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f".{fn.attr}() on a traced value",
                       "fetch after the kernel returns, not inside it")
        self.generic_visit(node)

    def _check_test(self, test: ast.AST, line: int, kind: str) -> None:
        if self._expr_tainted(test):
            self._flag("TRACER_BRANCH", line,
                       f"Python {kind} on a traced value",
                       "use lax.cond/lax.select/jnp.where or a mask; "
                       "host control flow cannot see device values")

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node.test, node.lineno, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node.test, node.lineno, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node.test, node.lineno, "conditional expression")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range"
                and any(self._expr_tainted(a) for a in it.args)):
            self._flag("TRACER_BRANCH", node.lineno,
                       "range() over a traced value",
                       "use lax.fori_loop/lax.scan for traced trip "
                       "counts")
        self.generic_visit(node)

    # nested defs: their params shadow the outer taint
    def _visit_nested(self, node) -> None:
        params = {a.arg for a in node.args.posonlyargs + node.args.args}
        saved = self.tainted
        self.tainted = saved - params
        self.generic_visit(node)
        self.tainted = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested(node)


def _lint_tracer_leaks(tree: ast.Module, file: str) -> List[Finding]:
    out: List[Finding] = []
    for kernel in _collect_kernels(tree):
        fn = kernel.fn
        if isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
            name = "<lambda>"
            body: Sequence[ast.AST] = [fn.body]
        else:
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
            name = fn.name
            body = fn.body
        tainted = params - kernel.static_names - {"self"}
        v = _TaintVisitor(file, name, tainted)
        for stmt in body:
            v.visit(stmt)
        out.extend(v.findings)
    return out


# -- registry-drift lints ---------------------------------------------------

def _str_arg(node: ast.Call, i: int = 0) -> Optional[str]:
    if len(node.args) > i and isinstance(node.args[i], ast.Constant) \
            and isinstance(node.args[i].value, str):
        return node.args[i].value
    return None


def _lint_fault_points(tree: ast.Module, file: str) -> List[Finding]:
    from flink_tpu.faults import KNOWN_FAULT_POINTS

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_fire = (
            (isinstance(fn, ast.Attribute) and fn.attr == "fire"
             and isinstance(fn.value, ast.Name)
             and fn.value.id == "faults")
            or (isinstance(fn, ast.Name) and fn.id == "fire"))
        if not is_fire:
            continue
        point = _str_arg(node)
        if point is not None and point not in KNOWN_FAULT_POINTS:
            out.append(_finding(
                "FAULT_POINT_DRIFT",
                f"faults.fire({point!r}) is not in "
                "faults.KNOWN_FAULT_POINTS — chaos rules targeting it "
                "can never be validated, and the analyzer will reject "
                "confs that name it", file, node.lineno,
                fix="add the point to KNOWN_FAULT_POINTS (and the "
                    "module docstring's point list) or fix the literal"))
    return out


def _lint_config_keys(tree: ast.Module, file: str) -> List[Finding]:
    from flink_tpu.config import is_declared_key

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        keys: List[Tuple[str, int]] = []
        if isinstance(fn, ast.Attribute) and fn.attr == "get_raw":
            k = _str_arg(node)
            if k is not None:
                keys.append((k, node.lineno))
        elif (isinstance(fn, (ast.Name, ast.Attribute))
              and (fn.attr if isinstance(fn, ast.Attribute) else fn.id)
              == "Configuration" and node.args
              and isinstance(node.args[0], ast.Dict)):
            for kn in node.args[0].keys:
                if isinstance(kn, ast.Constant) and isinstance(kn.value, str):
                    keys.append((kn.value, kn.lineno))
        for key, line in keys:
            if not is_declared_key(key):
                out.append(_finding(
                    "CONFIG_KEY_DRIFT",
                    f"config key {key!r} is outside the declared option "
                    "grammar — the runtime ignores it", file, line,
                    fix="declare a ConfigOption (or dynamic prefix) in "
                        "config.py, or fix the literal"))
    return out


def _option_decls(tree: ast.Module, file: str) -> List[Tuple[str, str, int]]:
    """(key, file, line) of every ConfigOption/duration_option literal."""
    decls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name in ("ConfigOption", "duration_option"):
            key = _str_arg(node)
            if key is not None:
                decls.append((key, file, node.lineno))
    return decls


def _lint_metric_names(tree: ast.Module, file: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        names: List[str] = []
        if fn.attr in _METRIC_KINDS:
            n = _str_arg(node)
            if n is not None:
                names.append(n)
        elif fn.attr == "group":
            names.extend(
                a.value for a in node.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str))
        for n in names:
            if not _METRIC_NAME_RE.match(n):
                out.append(_finding(
                    "METRIC_NAME_INVALID",
                    f"metric name {n!r} is outside the snake_case "
                    "grammar ([a-z0-9_] dotted segments) dashboards "
                    "key on", file, node.lineno,
                    fix="rename to lowercase snake_case"))
    return out


# -- entry points -----------------------------------------------------------

def lint_source(source: str, file: str) -> List[Finding]:
    """Lint one file's source text (the unit every test fixture uses)."""
    tree = ast.parse(source, filename=file)
    out: List[Finding] = []
    out.extend(_lint_tracer_leaks(tree, file))
    out.extend(_lint_fault_points(tree, file))
    out.extend(_lint_config_keys(tree, file))
    out.extend(_lint_metric_names(tree, file))
    return out


def repo_root() -> str:
    """The directory holding the flink_tpu package (lint path base)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


DEFAULT_LINT_PATHS = ("flink_tpu", "tools", "bench.py", "bench_micro.py")


def lint_paths(paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Lint every ``*.py`` under ``paths`` (files or directories,
    resolved against ``root`` — defaults to the shipped tree). Also
    runs the cross-file CONFIG_OPTION_DUP check over the whole set."""
    from flink_tpu.analysis.plan_rules import load_option_grammar

    load_option_grammar()
    root = root or repo_root()
    files: List[str] = []
    for p in (paths or DEFAULT_LINT_PATHS):
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full) and not os.path.isabs(p):
            full = os.path.abspath(p)  # CLI arg relative to the cwd
        if os.path.isfile(full):
            files.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, fnames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(fnames) if f.endswith(".py"))
        else:
            # a typo'd path silently linting NOTHING would leave a CI
            # drift gate green while checking nothing — fail loudly
            raise ValueError(f"lint path does not exist: {p!r} "
                             f"(resolved against {root!r} and the cwd)")
    out: List[Finding] = []
    decls: List[Tuple[str, str, int]] = []
    for f in sorted(set(files)):
        rel = os.path.relpath(f, root)
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src, filename=rel)
        out.extend(_lint_tracer_leaks(tree, rel))
        out.extend(_lint_fault_points(tree, rel))
        out.extend(_lint_config_keys(tree, rel))
        out.extend(_lint_metric_names(tree, rel))
        decls.extend(_option_decls(tree, rel))
    by_key: Dict[str, List[Tuple[str, str, int]]] = {}
    for key, file, line in decls:
        by_key.setdefault(key, []).append((key, file, line))
    for key, sites in sorted(by_key.items()):
        if len(sites) > 1:
            first = f"{sites[0][1]}:{sites[0][2]}"
            for _, file, line in sites[1:]:
                out.append(_finding(
                    "CONFIG_OPTION_DUP",
                    f"option key {key!r} already declared at {first} — "
                    "re-declaration silently replaces it in the "
                    "registry", file, line,
                    fix="reuse the existing ConfigOption constant"))
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out
