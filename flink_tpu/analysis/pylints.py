"""Repo AST lints — pure-stdlib ``ast`` pass over the codebase itself.

The runtime's correctness leans on conventions no unit test can see
whole: jit kernels must stay trace-pure (PROFILE §8.1's design rules
exist because host round-trips inside kernels silently retrace or
pin stale values), ``faults.fire`` literals must match the registry in
``faults.py`` (a drifted literal = a chaos plan that injects nothing),
and config/metric name literals must stay inside their declared
grammars (a typo'd key silently runs the default). Each lint is one
linear AST walk; `python -m flink_tpu lint` and the tier-1 dogfood
gate (tests/test_analysis.py) keep the shipped tree at zero findings.

Rule catalog:

- ``TRACER_HOST_CALL`` (error): ``float()/int()/bool()``,
  ``np.asarray()/np.array()``, ``.item()/.tolist()`` applied to a value
  derived from a traced parameter inside a directly-jitted kernel —
  a host materialization that breaks tracing (ConcretizationTypeError
  at best, a silently-stale constant at worst).
- ``TRACER_BRANCH`` (error): Python ``if``/``while``/ternary (or
  ``range()`` iteration) on a value derived from a traced parameter
  inside a jitted kernel — control flow must go through ``lax.cond`` /
  ``jnp.where`` / masking.
- ``FAULT_POINT_DRIFT`` (error): a ``faults.fire("...")`` literal not
  in ``faults.KNOWN_FAULT_POINTS``.
- ``CONFIG_KEY_DRIFT`` (error): a string key passed to
  ``.get_raw()`` / ``Configuration({...})`` that is outside the
  declared option grammar.
- ``CONFIG_OPTION_DUP`` (error): one option key declared by two
  ``ConfigOption``/``duration_option`` literals — last registration
  silently wins.
- ``METRIC_NAME_INVALID`` (warn): a metric/group name literal outside
  the ``[a-z0-9_]`` snake-case grammar every dashboard keys on.
- ``HOSTPOOL_SHARED_WRITE`` (warn): the CONCURRENCY plane — a closure
  submitted to ``HostPool.run_tasks`` assigns through a free variable
  (``self.total += n``, ``shared[k] = v``, ``nonlocal``/``global``)
  outside a ``with <...lock...>:`` guard. Pool tasks run on worker
  threads; an unguarded read-modify-write on shared state is exactly
  the race class PR 5 fixed by hand in ``obs/metrics.py`` (Counter's
  ``self._v += n``). The sanctioned disciplines (parallel/hostpool.py):
  RETURN a partial and let the caller combine (results come back in
  submission order), or guard the write with a lock whose name
  contains "lock" — the lint keys on the name.

Honest scope (linear, syntactic): "derived from a traced parameter"
is one assignment hop inside the kernel body — no fixpoint, no
cross-function taint, no aliasing. Values reached only through static
attributes (``.shape``/``.ndim``/``.dtype``/``.size``), ``len()``,
``is None`` / ``in`` tests are NOT tainted (those are static under
tracing). Only functions jitted DIRECTLY (``@jit`` decorators or
``jax.jit(f)`` / ``jax.jit(shard_map(f, ...))`` on a local def) are
kernels: a helper merely *called* from a kernel may legitimately
receive concrete Python values, so it is out of scope. The hostpool
lint covers closures reachable from the ``run_tasks`` call site — a
lambda/def in the argument list (incl. list literals/comprehensions),
a local name the file assigns/appends such closures to, and ONE call
hop into a local def the closure body invokes by name; writes through
closure PARAMETERS are per-task by convention and out of scope, as
are mutating method calls (``shared.append(x)``).
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from flink_tpu.analysis.core import Finding

# (rule id, severity, one-line description, fix hint) — the "pylint"
# plane of RULES.md (analysis/docs.py renders this next to the plan/
# config/dataflow catalog in core.rule_catalog_full()).
LINT_CATALOG: Tuple[Tuple[str, str, str, str], ...] = (
    ("TRACER_HOST_CALL", "error",
     "Host conversion (float/int/bool, np.asarray, .item/.tolist) on a "
     "traced value inside a jit kernel.",
     "keep it on device (jnp) or hoist the conversion out"),
    ("TRACER_BRANCH", "error",
     "Python if/while/ternary or range() on a traced value inside a "
     "jit kernel.",
     "use lax.cond / jnp.where / lax.fori_loop"),
    ("FAULT_POINT_DRIFT", "error",
     "A faults.fire literal outside faults.KNOWN_FAULT_POINTS.",
     "register the point or fix the literal"),
    ("CONFIG_KEY_DRIFT", "error",
     "A get_raw/Configuration key literal outside the declared option "
     "grammar.",
     "declare a ConfigOption / dynamic prefix, or fix the literal"),
    ("CONFIG_OPTION_DUP", "error",
     "One option key declared by two ConfigOption literals — last "
     "registration silently wins.",
     "reuse the existing ConfigOption constant"),
    ("METRIC_NAME_INVALID", "warn",
     "A metric/group name literal outside the snake_case grammar.",
     "rename to lowercase snake_case"),
    ("HOSTPOOL_SHARED_WRITE", "warn",
     "A closure submitted to HostPool.run_tasks writes shared mutable "
     "state (free-variable attribute/subscript target, nonlocal/"
     "global) outside a lock guard.",
     "guard the write with a lock, or return a partial and combine on "
     "the caller"),
)
LINT_RULES: Tuple[Tuple[str, str], ...] = tuple(
    (r, s) for r, s, _, _ in LINT_CATALOG)
_SEV = dict(LINT_RULES)

_METRIC_KINDS = ("counter", "gauge", "meter", "histogram")
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)*$")
# attribute reads that are STATIC under tracing — a name reached only
# through these never carries the tracer into host code
_STATIC_ATTRS = frozenset(("shape", "ndim", "dtype", "size"))
_HOST_CONVERSIONS = frozenset(("float", "int", "bool"))
_HOST_METHODS = frozenset(("item", "tolist"))
_NP_MATERIALIZERS = frozenset(("asarray", "array"))


def _finding(rule: str, message: str, file: str, line: int,
             fix: str = "") -> Finding:
    return Finding(rule=rule, severity=_SEV[rule], message=message,
                   fix=fix, file=file, line=line)


# -- jit-kernel discovery ---------------------------------------------------

@dataclasses.dataclass
class _Kernel:
    fn: ast.AST                  # FunctionDef / AsyncFunctionDef / Lambda
    static_names: Set[str]


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` (any attribute path ending in .jit)."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return False


def _static_names(jit_call: Optional[ast.Call],
                  fn: ast.AST) -> Set[str]:
    """Param names excluded from tracing via static_argnums/names."""
    out: Set[str] = set()
    if jit_call is None:
        return out
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        out.add(params[c.value])
        elif kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


def _collect_kernels(tree: ast.Module) -> List[_Kernel]:
    """Functions DIRECTLY jitted in this file: decorator forms
    (``@jit``, ``@jax.jit``, ``@partial(jax.jit, ...)``,
    ``@jax.jit(...)`` with kwargs) and call forms (``jax.jit(f)``,
    ``jax.jit(shard_map(f, ...))`` where ``f`` is a local def)."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    kernels: List[_Kernel] = []
    seen: Set[int] = set()

    def add(fn: ast.AST, jit_call: Optional[ast.Call]) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        kernels.append(_Kernel(fn, _static_names(jit_call, fn)))

    for node in ast.walk(tree):
        # decorator forms
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    add(node, None)
                elif isinstance(dec, ast.Call):
                    if _is_jit_expr(dec.func):
                        add(node, dec)
                    elif (isinstance(dec.func, (ast.Name, ast.Attribute))
                          and (dec.func.attr if isinstance(
                              dec.func, ast.Attribute) else dec.func.id)
                          == "partial"
                          and dec.args and _is_jit_expr(dec.args[0])):
                        add(node, dec)
        # call forms: jax.jit(f) / jax.jit(shard_map(f, ...))
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            if not node.args:
                continue
            target = node.args[0]
            if (isinstance(target, ast.Call)
                    and isinstance(target.func, (ast.Name, ast.Attribute))
                    and (target.func.attr if isinstance(
                        target.func, ast.Attribute) else target.func.id)
                    == "shard_map" and target.args):
                target = target.args[0]
            if isinstance(target, ast.Name):
                for fn in defs_by_name.get(target.id, ()):
                    add(fn, node)
            elif isinstance(target, ast.Lambda):
                add(target, node)
    return kernels


# -- taint walk over one kernel body ----------------------------------------

class _TaintVisitor(ast.NodeVisitor):
    """One in-order pass over a kernel body. ``tainted`` starts as the
    traced parameter set; a single assignment hop propagates it. The
    visitor flags host conversions and Python control flow on tainted
    expressions."""

    def __init__(self, file: str, kernel_name: str,
                 tainted: Set[str]) -> None:
        self.file = file
        self.kernel = kernel_name
        self.tainted = set(tainted)
        self.findings: List[Finding] = []

    # -- taint test -------------------------------------------------------
    def _expr_tainted(self, node: ast.AST) -> bool:
        """Does this expression carry a traced value into host code?
        Names under static attributes / len() / `is`/`in` tests don't."""
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "len":
                return False  # len() of arrays/dicts is static
            if isinstance(fn, ast.Name) and fn.id == "isinstance":
                return False
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops):
            # `x is None` / `"col" in data` are static under tracing
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        return any(self._expr_tainted(c) for c in ast.iter_child_nodes(node))

    # -- taint propagation (one hop, source order) ------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if self._expr_tainted(node.value):
            self.tainted.update(names)
        else:
            self.tainted.difference_update(names)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if (isinstance(node.target, ast.Name)
                and self._expr_tainted(node.value)):
            self.tainted.add(node.target.id)

    # -- flagged sites ----------------------------------------------------
    def _flag(self, rule: str, line: int, what: str, fix: str) -> None:
        self.findings.append(_finding(
            rule, f"{what} inside jit kernel {self.kernel!r} — host "
            "round-trips on traced values retrace or pin stale "
            "constants", self.file, line, fix=fix))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Name) and fn.id in _HOST_CONVERSIONS
                and node.args and self._expr_tainted(node.args[0])):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f"{fn.id}() on a traced value",
                       "keep it on device (jnp.astype/where) or hoist "
                       "the conversion out of the kernel")
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _NP_MATERIALIZERS
              and isinstance(fn.value, ast.Name)
              and fn.value.id in ("np", "numpy")
              and node.args and self._expr_tainted(node.args[0])):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f"np.{fn.attr}() on a traced value",
                       "use jnp inside kernels; numpy materializes on "
                       "the host")
        elif (isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS
              and self._expr_tainted(fn.value)):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f".{fn.attr}() on a traced value",
                       "fetch after the kernel returns, not inside it")
        self.generic_visit(node)

    def _check_test(self, test: ast.AST, line: int, kind: str) -> None:
        if self._expr_tainted(test):
            self._flag("TRACER_BRANCH", line,
                       f"Python {kind} on a traced value",
                       "use lax.cond/lax.select/jnp.where or a mask; "
                       "host control flow cannot see device values")

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node.test, node.lineno, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node.test, node.lineno, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node.test, node.lineno, "conditional expression")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range"
                and any(self._expr_tainted(a) for a in it.args)):
            self._flag("TRACER_BRANCH", node.lineno,
                       "range() over a traced value",
                       "use lax.fori_loop/lax.scan for traced trip "
                       "counts")
        self.generic_visit(node)

    # nested defs: their params shadow the outer taint
    def _visit_nested(self, node) -> None:
        params = {a.arg for a in node.args.posonlyargs + node.args.args}
        saved = self.tainted
        self.tainted = saved - params
        self.generic_visit(node)
        self.tainted = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested(node)


def _lint_tracer_leaks(tree: ast.Module, file: str) -> List[Finding]:
    out: List[Finding] = []
    for kernel in _collect_kernels(tree):
        fn = kernel.fn
        if isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
            name = "<lambda>"
            body: Sequence[ast.AST] = [fn.body]
        else:
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
            name = fn.name
            body = fn.body
        tainted = params - kernel.static_names - {"self"}
        v = _TaintVisitor(file, name, tainted)
        for stmt in body:
            v.visit(stmt)
        out.extend(v.findings)
    return out


# -- registry-drift lints ---------------------------------------------------

def _str_arg(node: ast.Call, i: int = 0) -> Optional[str]:
    if len(node.args) > i and isinstance(node.args[i], ast.Constant) \
            and isinstance(node.args[i].value, str):
        return node.args[i].value
    return None


def _lint_fault_points(tree: ast.Module, file: str) -> List[Finding]:
    from flink_tpu.faults import KNOWN_FAULT_POINTS

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_fire = (
            (isinstance(fn, ast.Attribute) and fn.attr == "fire"
             and isinstance(fn.value, ast.Name)
             and fn.value.id == "faults")
            or (isinstance(fn, ast.Name) and fn.id == "fire"))
        if not is_fire:
            continue
        point = _str_arg(node)
        if point is not None and point not in KNOWN_FAULT_POINTS:
            out.append(_finding(
                "FAULT_POINT_DRIFT",
                f"faults.fire({point!r}) is not in "
                "faults.KNOWN_FAULT_POINTS — chaos rules targeting it "
                "can never be validated, and the analyzer will reject "
                "confs that name it", file, node.lineno,
                fix="add the point to KNOWN_FAULT_POINTS (and the "
                    "module docstring's point list) or fix the literal"))
    return out


def _lint_config_keys(tree: ast.Module, file: str) -> List[Finding]:
    from flink_tpu.config import is_declared_key

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        keys: List[Tuple[str, int]] = []
        if isinstance(fn, ast.Attribute) and fn.attr == "get_raw":
            k = _str_arg(node)
            if k is not None:
                keys.append((k, node.lineno))
        elif (isinstance(fn, (ast.Name, ast.Attribute))
              and (fn.attr if isinstance(fn, ast.Attribute) else fn.id)
              == "Configuration" and node.args
              and isinstance(node.args[0], ast.Dict)):
            for kn in node.args[0].keys:
                if isinstance(kn, ast.Constant) and isinstance(kn.value, str):
                    keys.append((kn.value, kn.lineno))
        for key, line in keys:
            if not is_declared_key(key):
                out.append(_finding(
                    "CONFIG_KEY_DRIFT",
                    f"config key {key!r} is outside the declared option "
                    "grammar — the runtime ignores it", file, line,
                    fix="declare a ConfigOption (or dynamic prefix) in "
                        "config.py, or fix the literal"))
    return out


def _option_decls(tree: ast.Module, file: str) -> List[Tuple[str, str, int]]:
    """(key, file, line) of every ConfigOption/duration_option literal."""
    decls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name in ("ConfigOption", "duration_option"):
            key = _str_arg(node)
            if key is not None:
                decls.append((key, file, node.lineno))
    return decls


def _lint_metric_names(tree: ast.Module, file: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        names: List[str] = []
        if fn.attr in _METRIC_KINDS:
            n = _str_arg(node)
            if n is not None:
                names.append(n)
        elif fn.attr == "group":
            names.extend(
                a.value for a in node.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str))
        for n in names:
            if not _METRIC_NAME_RE.match(n):
                out.append(_finding(
                    "METRIC_NAME_INVALID",
                    f"metric name {n!r} is outside the snake_case "
                    "grammar ([a-z0-9_] dotted segments) dashboards "
                    "key on", file, node.lineno,
                    fix="rename to lowercase snake_case"))
    return out


# -- concurrency lint: shared writes in HostPool.run_tasks closures ---------

def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name of an Attribute/Subscript chain (``self`` of
    ``self.panes[p]``), or None when the base is not a plain name."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _lock_guarded_expr(node: ast.AST) -> bool:
    """A with-item context expression that names a lock (any Name or
    attribute segment containing 'lock', case-insensitive) — the
    discipline marker parallel/hostpool.py documents."""
    for c in ast.walk(node):
        if isinstance(c, ast.Name) and "lock" in c.id.lower():
            return True
        if isinstance(c, ast.Attribute) and "lock" in c.attr.lower():
            return True
    return False


class _SharedWriteVisitor(ast.NodeVisitor):
    """Walk one task closure's body: flag Assign/AugAssign whose target
    routes through a FREE variable (not a parameter, not a local)
    unless the statement sits under a with-lock guard."""

    def __init__(self, file: str, closure_name: str,
                 local_names: Set[str]) -> None:
        self.file = file
        self.closure = closure_name
        self.locals = set(local_names)
        self.lock_depth = 0
        self.findings: List[Finding] = []

    def _flag(self, line: int, target_src: str) -> None:
        self.findings.append(_finding(
            "HOSTPOOL_SHARED_WRITE",
            f"task closure {self.closure!r} writes shared state "
            f"({target_src}) without a lock — run_tasks executes it on "
            "a pool worker thread; unguarded read-modify-writes lose "
            "updates (the obs/metrics.py Counter race class)",
            self.file, line,
            fix="guard the write with a `with <lock>:` block, or "
                "return a partial and combine on the caller (results "
                "arrive in submission order)"))

    def _check_target(self, target: ast.AST, line: int) -> None:
        if self.lock_depth > 0:
            return
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            root = _root_name(target)
            if root is not None and root not in self.locals:
                self._flag(line, ast.unparse(target))
        elif isinstance(target, ast.Name):
            # a bare-name write is local unless declared otherwise
            # (visit_Nonlocal/Global remove such names from `locals`)
            if target.id not in self.locals:
                self._flag(line, target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_target(el, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self.locals.difference_update(node.names)

    def visit_Global(self, node: ast.Global) -> None:
        self.locals.difference_update(node.names)

    def visit_With(self, node: ast.With) -> None:
        guarded = any(_lock_guarded_expr(i.context_expr)
                      for i in node.items)
        if guarded:
            self.lock_depth += 1
        self.generic_visit(node)
        if guarded:
            self.lock_depth -= 1

    # nested defs/lambdas get their own scope; don't descend (only the
    # submitted closure and its one-hop callee are in scope)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


def _fn_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _fn_locals(fn: ast.AST) -> Set[str]:
    """Parameters + bare names the body binds (assignments, for/with
    targets, comprehension-free walk at this scope)."""
    names = _fn_params(fn)
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    for stmt in body:
        for c in ast.walk(stmt):
            if isinstance(c, ast.Assign):
                for t in c.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(c, (ast.AnnAssign, ast.AugAssign,
                                ast.NamedExpr)):
                # `n: int = 0`, `n += 1` (local unless nonlocal/global
                # — the visitor re-frees declared names), `(n := ...)`
                if isinstance(c.target, ast.Name):
                    names.add(c.target.id)
            elif isinstance(c, (ast.For, ast.AsyncFor)):
                for t in ast.walk(c.target):
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(c, (ast.With, ast.AsyncWith)):
                for i in c.items:
                    if isinstance(i.optional_vars, ast.Name):
                        names.add(i.optional_vars.id)
    return names


def _called_local_defs(fn: ast.AST,
                       defs_by_name: Dict[str, List[ast.AST]]
                       ) -> List[ast.AST]:
    """Local defs the closure body calls BY NAME — one call hop (the
    `run_tasks([lambda a=a: merge(a)])` shape, where the real body
    lives in `merge`)."""
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    out: List[ast.AST] = []
    for stmt in body:
        for c in ast.walk(stmt):
            if isinstance(c, ast.Call) and isinstance(c.func, ast.Name):
                out.extend(defs_by_name.get(c.func.id, ()))
    return out


def _lint_hostpool_writes(tree: ast.Module, file: str) -> List[Finding]:
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    # name → closures the file binds into it (list/tuple literals,
    # listcomp values, .append(lambda ...) / .append(local_def)) —
    # resolves `run_tasks(tasks)`. Name references resolve to local
    # defs only where the expression IS the closure (a bare name, a
    # literal element, a comprehension elt) — resolving every Name in
    # an arbitrary value would mis-tag caller-thread helpers as tasks.
    bound: Dict[str, List[ast.AST]] = {}

    def closures_in(expr: ast.AST) -> List[ast.AST]:
        out = [c for c in ast.walk(expr) if isinstance(c, ast.Lambda)]
        names: List[str] = []
        if isinstance(expr, ast.Name):
            names = [expr.id]
        elif isinstance(expr, (ast.List, ast.Tuple)):
            names = [e.id for e in expr.elts if isinstance(e, ast.Name)]
        elif isinstance(expr, (ast.ListComp, ast.GeneratorExp)) \
                and isinstance(expr.elt, ast.Name):
            names = [expr.elt.id]
        for nm in names:
            out.extend(bound.get(nm, ()))
            out.extend(defs_by_name.get(nm, ()))
        return out

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            closures = closures_in(node.value)
            if closures:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        bound.setdefault(t.id, []).extend(closures)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "append"
              and isinstance(node.func.value, ast.Name)):
            for a in node.args:
                bound.setdefault(node.func.value.id, []).extend(
                    closures_in(a))

    out: List[Finding] = []
    seen: Set[int] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "run_tasks"):
            continue
        closures: List[ast.AST] = []
        for a in node.args:
            closures.extend(closures_in(a))
        for fn in closures:
            hops = [fn] + _called_local_defs(fn, defs_by_name)
            for body_fn in hops:
                if id(body_fn) in seen:
                    continue
                seen.add(id(body_fn))
                name = getattr(body_fn, "name", "<lambda>")
                v = _SharedWriteVisitor(file, name, _fn_locals(body_fn))
                body = ([body_fn.body] if isinstance(body_fn, ast.Lambda)
                        else body_fn.body)
                for stmt in body:
                    v.visit(stmt)
                out.extend(v.findings)
    return out


# -- entry points -----------------------------------------------------------

def lint_source(source: str, file: str) -> List[Finding]:
    """Lint one file's source text (the unit every test fixture uses)."""
    tree = ast.parse(source, filename=file)
    out: List[Finding] = []
    out.extend(_lint_tracer_leaks(tree, file))
    out.extend(_lint_fault_points(tree, file))
    out.extend(_lint_config_keys(tree, file))
    out.extend(_lint_metric_names(tree, file))
    out.extend(_lint_hostpool_writes(tree, file))
    return out


def repo_root() -> str:
    """The directory holding the flink_tpu package (lint path base)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


DEFAULT_LINT_PATHS = ("flink_tpu", "tools", "bench.py", "bench_micro.py")


def lint_paths(paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Lint every ``*.py`` under ``paths`` (files or directories,
    resolved against ``root`` — defaults to the shipped tree). Also
    runs the cross-file CONFIG_OPTION_DUP check over the whole set."""
    from flink_tpu.analysis.plan_rules import load_option_grammar

    load_option_grammar()
    root = root or repo_root()
    files: List[str] = []
    for p in (paths or DEFAULT_LINT_PATHS):
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full) and not os.path.isabs(p):
            full = os.path.abspath(p)  # CLI arg relative to the cwd
        if os.path.isfile(full):
            files.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, fnames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(fnames) if f.endswith(".py"))
        else:
            # a typo'd path silently linting NOTHING would leave a CI
            # drift gate green while checking nothing — fail loudly
            raise ValueError(f"lint path does not exist: {p!r} "
                             f"(resolved against {root!r} and the cwd)")
    out: List[Finding] = []
    decls: List[Tuple[str, str, int]] = []
    for f in sorted(set(files)):
        rel = os.path.relpath(f, root)
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src, filename=rel)
        out.extend(_lint_tracer_leaks(tree, rel))
        out.extend(_lint_fault_points(tree, rel))
        out.extend(_lint_config_keys(tree, rel))
        out.extend(_lint_metric_names(tree, rel))
        out.extend(_lint_hostpool_writes(tree, rel))
        decls.extend(_option_decls(tree, rel))
    by_key: Dict[str, List[Tuple[str, str, int]]] = {}
    for key, file, line in decls:
        by_key.setdefault(key, []).append((key, file, line))
    for key, sites in sorted(by_key.items()):
        if len(sites) > 1:
            first = f"{sites[0][1]}:{sites[0][2]}"
            for _, file, line in sites[1:]:
                out.append(_finding(
                    "CONFIG_OPTION_DUP",
                    f"option key {key!r} already declared at {first} — "
                    "re-declaration silently replaces it in the "
                    "registry", file, line,
                    fix="reuse the existing ConfigOption constant"))
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out
