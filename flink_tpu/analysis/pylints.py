"""Repo AST lints — pure-stdlib ``ast`` pass over the codebase itself.

The runtime's correctness leans on conventions no unit test can see
whole: jit kernels must stay trace-pure (PROFILE §8.1's design rules
exist because host round-trips inside kernels silently retrace or
pin stale values), ``faults.fire`` literals must match the registry in
``faults.py`` (a drifted literal = a chaos plan that injects nothing),
config/metric name literals must stay inside their declared grammars,
durable tiers must route writes through the fs.py seam (PR 14), and
the epoch-fenced lease discipline (PRs 9/18) must gate every fenced
publication. ``python -m flink_tpu lint`` and the tier-1 dogfood gate
(tests/test_analysis.py) keep the shipped tree at zero findings.

The pass is INTERPROCEDURAL: every linted file is indexed into one
project call graph (``analysis/callgraph.py`` — defs, methods via
self-type, import aliases, binding-type lock tracking), and the rules
that need it follow calls to arbitrary depth. Rules group into PLANES
(the ``--plane`` CLI filter keys on these):

- ``tracer`` — TRACER_HOST_CALL / TRACER_BRANCH (error): host
  conversions (``float()/int()/bool()``, ``np.asarray``,
  ``.item()/.tolist()``) or Python control flow on a value derived
  from a traced parameter, inside a jitted kernel OR any helper the
  kernel's traced arguments flow into (taint maps actuals to formals
  across resolved calls; a helper that only ever receives concrete
  values stays out of scope).
- ``registry`` — FAULT_POINT_DRIFT (error): a ``faults.fire`` literal
  outside ``faults.KNOWN_FAULT_POINTS``; FAULT_POINT_UNFIRED (warn),
  the REVERSE direction: a registered point with no fire site
  anywhere in the linted set is dead registry. Fire sites resolve
  through module string constants (``fire(TASK_FAULT_POINT)``) and
  one parameter-forwarding hop (``fire(fsync_point)`` + a call site
  passing ``fsync_point="state.run.fsync"``); intentionally
  registered-first points live in ``faults.UNFIRED_ALLOWLIST``. The
  rule only runs when the linted set contains the registry
  assignment itself — lint the whole tree for a meaningful result.
- ``config`` — CONFIG_KEY_DRIFT / CONFIG_OPTION_DUP (error): literals
  outside the declared option grammar / duplicate declarations.
- ``metrics`` — METRIC_NAME_INVALID (warn): names outside the
  snake_case grammar dashboards key on.
- ``concurrency`` — HOSTPOOL_SHARED_WRITE (warn): a closure submitted
  to ``HostPool.run_tasks`` assigns through a free variable outside a
  lock guard, followed through ANY number of same-module call hops
  (a helper called with shared state keeps the shared tag on the
  bound formal). Locks are recognized by BINDING TYPE — a name or
  ``self`` attribute assigned ``threading.Lock()/RLock()/...`` —
  with the legacy ``*lock*`` name-substring accepted for locks that
  arrive as parameters.
- ``durability`` — DURABILITY_SEAM_BYPASS (error): a raw
  ``open(..., 'w')`` / ``os.fsync`` / ``os.replace`` / ``os.rename``
  in a durable-tier module (the PR-14 seam contract; the
  tests/test_architecture.py gate is a thin wrapper over this rule).
  ``os.rename`` of lock/lease/grave files is the documented
  local-lock-primitive residue and exempt.
- ``locking`` — LOCK_ORDER_CYCLE (warn): a lock-acquisition graph
  from nested ``with`` guards ACROSS call edges; two tracked locks
  taken in opposite orders on two paths is a potential ABBA
  deadlock, reported with both acquisition paths named. Reentrant
  self-acquisition (RLock) is not an edge.
- ``fencing`` — FENCE_UNVERIFIED_PUBLISH (error): in a LEASED class
  (one whose methods call ``self.<attr>.verify(...)``), a public
  method that reaches a ``write_atomic``/``put_if`` of a fenced
  record (marker/manifest/offset/status/membership path text) with
  no lease ``verify()``/renew earlier on the path — the PR-9/18
  fencing discipline checked statically. Publishing the lease/lock
  record itself IS the fence and is exempt.

Honest scope (syntactic, flow-insensitive): name resolution is the
call graph's — no values-as-functions, no conditional rebinding, no
symbolic shapes. Taint has no aliasing; values reached only through
static attributes (``.shape``/``.ndim``/``.dtype``/``.size``),
``len()``, ``is``/``in`` tests are NOT tainted. Only functions jitted
DIRECTLY are kernel roots. Hostpool closure discovery is unchanged:
lambdas/defs in the ``run_tasks`` argument list, names the file binds
such closures to; writes through per-task PARAMETERS stay out of
scope, as do mutating method calls (``shared.append(x)``). Fence/
lock-order walks flatten branches in source order (a fence inside an
``if`` counts).
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from flink_tpu.analysis.core import Finding
from flink_tpu.analysis.callgraph import (
    LOCK_CONSTRUCTORS,
    CallGraph,
    FuncInfo,
    ModuleInfo,
    _call_ctor_name,
    build_graph,
)

# (rule id, severity, plane, one-line description, fix hint) — the
# "pylint" planes of RULES.md (analysis/docs.py renders this next to
# the plan/config/dataflow catalog in core.rule_catalog_full()).
LINT_CATALOG: Tuple[Tuple[str, str, str, str, str], ...] = (
    ("TRACER_HOST_CALL", "error", "tracer",
     "Host conversion (float/int/bool, np.asarray, .item/.tolist) on a "
     "traced value inside a jit kernel or a helper its traced "
     "arguments flow into.",
     "keep it on device (jnp) or hoist the conversion out"),
    ("TRACER_BRANCH", "error", "tracer",
     "Python if/while/ternary or range() on a traced value inside a "
     "jit kernel or a helper its traced arguments flow into.",
     "use lax.cond / jnp.where / lax.fori_loop"),
    ("FAULT_POINT_DRIFT", "error", "registry",
     "A faults.fire literal outside faults.KNOWN_FAULT_POINTS.",
     "register the point or fix the literal"),
    ("FAULT_POINT_UNFIRED", "warn", "registry",
     "A registered fault point with no faults.fire site anywhere in "
     "the linted tree — dead registry chaos plans can never hit.",
     "instrument the seam with faults.fire, delete the point, or add "
     "it to faults.UNFIRED_ALLOWLIST"),
    ("CONFIG_KEY_DRIFT", "error", "config",
     "A get_raw/Configuration key literal outside the declared option "
     "grammar.",
     "declare a ConfigOption / dynamic prefix, or fix the literal"),
    ("CONFIG_OPTION_DUP", "error", "config",
     "One option key declared by two ConfigOption literals — last "
     "registration silently wins.",
     "reuse the existing ConfigOption constant"),
    ("METRIC_NAME_INVALID", "warn", "metrics",
     "A metric/group name literal outside the snake_case grammar.",
     "rename to lowercase snake_case"),
    ("HOSTPOOL_SHARED_WRITE", "warn", "concurrency",
     "A closure submitted to HostPool.run_tasks writes shared mutable "
     "state (free-variable attribute/subscript target, nonlocal/"
     "global) outside a lock guard, at any call depth.",
     "guard the write with a lock, or return a partial and combine on "
     "the caller"),
    ("DURABILITY_SEAM_BYPASS", "error", "durability",
     "A raw open(mode w/a/+), os.fsync, os.replace or os.rename in a "
     "durable-tier module bypasses the fs.py FileSystem seam.",
     "route through fs.open_write(sync=)/fs.fsync/fs.rename/"
     "write_atomic"),
    ("LOCK_ORDER_CYCLE", "warn", "locking",
     "Two tracked locks acquired in opposite orders on two call paths "
     "— a potential ABBA deadlock.",
     "pick one global acquisition order (lock hierarchy) or collapse "
     "them into one lock"),
    ("FENCE_UNVERIFIED_PUBLISH", "error", "fencing",
     "A fenced record (marker/manifest/offset/status/membership) "
     "published from a leased class's method with no lease "
     "verify()/renew on the path.",
     "call the lease verify()/renew gate before the publication"),
)
LINT_RULES: Tuple[Tuple[str, str], ...] = tuple(
    (r, s) for r, s, _p, _d, _f in LINT_CATALOG)
LINT_PLANES: Dict[str, str] = {r: p for r, _s, p, _d, _f in LINT_CATALOG}
_SEV = dict(LINT_RULES)

_METRIC_KINDS = ("counter", "gauge", "meter", "histogram")
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)*$")
# attribute reads that are STATIC under tracing — a name reached only
# through these never carries the tracer into host code
_STATIC_ATTRS = frozenset(("shape", "ndim", "dtype", "size"))
_HOST_CONVERSIONS = frozenset(("float", "int", "bool"))
_HOST_METHODS = frozenset(("item", "tolist"))
_NP_MATERIALIZERS = frozenset(("asarray", "array"))

# the tiers whose on-disk state must survive a power cut — the PR-14
# seam contract (tests/test_architecture.py gates on this rule)
DURABLE_MODULES = frozenset(
    "flink_tpu/" + m for m in (
        "log/topic.py", "log/bus.py", "log/connectors.py",
        "checkpoint/storage.py", "checkpoint/coordinator.py",
        "api/sinks.py", "connectors.py",
        "runtime/ha.py", "runtime/blob.py", "runtime/session.py",
        "fsck.py", "state/lsm.py"))

# path-text tokens that mark a FENCED record (the 2PC markers, the
# compaction/LSM manifests, group offsets/membership, cleaner status)
_FENCED_TOKENS = ("marker", "manifest", "offset", "status", "membership")

_TAINT_DEPTH = 8        # tracer call-descent cap
_POOL_DEPTH = 6         # hostpool call-descent cap
_FENCE_DEPTH = 6        # fence-walk call-descent cap


def _finding(rule: str, message: str, file: str, line: int,
             fix: str = "") -> Finding:
    return Finding(rule=rule, severity=_SEV[rule], message=message,
                   fix=fix, file=file, line=line)


def _iter_skip_nested(node: ast.AST):
    """Pre-order (source-order) walk that does NOT enter nested
    function/lambda bodies — they run in another frame (or thread)."""
    for c in ast.iter_child_nodes(node):
        yield c
        if not isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            yield from _iter_skip_nested(c)


def _enclosing_map(mi: ModuleInfo) -> Dict[int, FuncInfo]:
    """id(node) -> innermost enclosing FuncInfo for every node inside
    any indexed function of the module."""
    fis = [fi for fns in mi.functions.values() for fi in fns]
    # largest subtrees first so inner defs overwrite their enclosers
    sized = sorted(((len(list(ast.walk(fi.node))), fi) for fi in fis),
                   key=lambda t: -t[0])
    encl: Dict[int, FuncInfo] = {}
    for _, fi in sized:
        for n in ast.walk(fi.node):
            encl[id(n)] = fi
    return encl


def _all_param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


# -- jit-kernel discovery ---------------------------------------------------

@dataclasses.dataclass
class _Kernel:
    fn: ast.AST                  # FunctionDef / AsyncFunctionDef / Lambda
    static_names: Set[str]


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` (any attribute path ending in .jit)."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return False


def _static_names(jit_call: Optional[ast.Call],
                  fn: ast.AST) -> Set[str]:
    """Param names excluded from tracing via static_argnums/names."""
    out: Set[str] = set()
    if jit_call is None:
        return out
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        out.add(params[c.value])
        elif kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


def _collect_kernels(mi: ModuleInfo) -> List[_Kernel]:
    """Functions DIRECTLY jitted in this file: decorator forms
    (``@jit``, ``@jax.jit``, ``@partial(jax.jit, ...)``,
    ``@jax.jit(...)`` with kwargs) and call forms (``jax.jit(f)``,
    ``jax.jit(shard_map(f, ...))`` where ``f`` is a local def)."""
    defs_by_name: Dict[str, List[ast.AST]] = {
        name: [fi.node for fi in fns]
        for name, fns in mi.functions.items()}

    kernels: List[_Kernel] = []
    seen: Set[int] = set()

    def add(fn: ast.AST, jit_call: Optional[ast.Call]) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        kernels.append(_Kernel(fn, _static_names(jit_call, fn)))

    for node in mi.nodes:
        # decorator forms
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    add(node, None)
                elif isinstance(dec, ast.Call):
                    if _is_jit_expr(dec.func):
                        add(node, dec)
                    elif (isinstance(dec.func, (ast.Name, ast.Attribute))
                          and (dec.func.attr if isinstance(
                              dec.func, ast.Attribute) else dec.func.id)
                          == "partial"
                          and dec.args and _is_jit_expr(dec.args[0])):
                        add(node, dec)
        # call forms: jax.jit(f) / jax.jit(shard_map(f, ...))
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            if not node.args:
                continue
            target = node.args[0]
            if (isinstance(target, ast.Call)
                    and isinstance(target.func, (ast.Name, ast.Attribute))
                    and (target.func.attr if isinstance(
                        target.func, ast.Attribute) else target.func.id)
                    == "shard_map" and target.args):
                target = target.args[0]
            if isinstance(target, ast.Name):
                for fn in defs_by_name.get(target.id, ()):
                    add(fn, node)
            elif isinstance(target, ast.Lambda):
                add(target, node)
    return kernels


# -- taint walk over a kernel body and the helpers it reaches ---------------

class _TaintVisitor(ast.NodeVisitor):
    """One in-order pass over a function body. ``tainted`` starts as
    the traced parameter set; a single assignment hop propagates it
    within the body, and resolved calls with tainted actuals recurse
    into the callee with the matching FORMALS tainted (the
    interprocedural extension). The visitor flags host conversions and
    Python control flow on tainted expressions."""

    def __init__(self, graph: CallGraph, mi: ModuleInfo,
                 ctx: Optional[FuncInfo], file: str, where: str,
                 kernel: str, tainted: Set[str],
                 visited: Set[Tuple[int, frozenset]],
                 depth: int = 0) -> None:
        self.graph = graph
        self.mi = mi
        self.ctx = ctx
        self.file = file
        self.where = where          # "jit kernel 'k'" / helper phrasing
        self.kernel = kernel
        self.tainted = set(tainted)
        self.visited = visited
        self.depth = depth
        self.findings: List[Finding] = []

    # -- taint test -------------------------------------------------------
    def _expr_tainted(self, node: ast.AST) -> bool:
        """Does this expression carry a traced value into host code?
        Names under static attributes / len() / `is`/`in` tests don't."""
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "len":
                return False  # len() of arrays/dicts is static
            if isinstance(fn, ast.Name) and fn.id == "isinstance":
                return False
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops):
            # `x is None` / `"col" in data` are static under tracing
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        return any(self._expr_tainted(c) for c in ast.iter_child_nodes(node))

    # -- taint propagation (one hop, source order) ------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if self._expr_tainted(node.value):
            self.tainted.update(names)
        else:
            self.tainted.difference_update(names)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if (isinstance(node.target, ast.Name)
                and self._expr_tainted(node.value)):
            self.tainted.add(node.target.id)

    # -- flagged sites ----------------------------------------------------
    def _flag(self, rule: str, line: int, what: str, fix: str) -> None:
        self.findings.append(_finding(
            rule, f"{what} inside {self.where} — host round-trips on "
            "traced values retrace or pin stale constants",
            self.file, line, fix=fix))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Name) and fn.id in _HOST_CONVERSIONS
                and node.args and self._expr_tainted(node.args[0])):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f"{fn.id}() on a traced value",
                       "keep it on device (jnp.astype/where) or hoist "
                       "the conversion out of the kernel")
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _NP_MATERIALIZERS
              and isinstance(fn.value, ast.Name)
              and fn.value.id in ("np", "numpy")
              and node.args and self._expr_tainted(node.args[0])):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f"np.{fn.attr}() on a traced value",
                       "use jnp inside kernels; numpy materializes on "
                       "the host")
        elif (isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS
              and self._expr_tainted(fn.value)):
            self._flag("TRACER_HOST_CALL", node.lineno,
                       f".{fn.attr}() on a traced value",
                       "fetch after the kernel returns, not inside it")
        self._descend(node)
        self.generic_visit(node)

    def _descend(self, node: ast.Call) -> None:
        """Map tainted actuals to formals of every resolvable callee
        and lint the callee body under that taint set."""
        if self.depth >= _TAINT_DEPTH:
            return
        for fi in self.graph.resolve(node, self.ctx, self.mi):
            pos = fi.params()
            offset = 1 if (fi.is_method and pos[:1] == ["self"]
                           and isinstance(node.func, ast.Attribute)) else 0
            names = set(_all_param_names(fi.node))
            tset: Set[str] = set()
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Starred):
                    continue
                j = i + offset
                if j < len(pos) and self._expr_tainted(arg):
                    tset.add(pos[j])
            for kw in node.keywords:
                if kw.arg and kw.arg in names \
                        and self._expr_tainted(kw.value):
                    tset.add(kw.arg)
            if not tset:
                continue  # only concrete values flow in — out of scope
            key = (id(fi.node), frozenset(tset))
            if key in self.visited:
                continue
            self.visited.add(key)
            sub = _TaintVisitor(
                self.graph, self.graph.modules.get(fi.module, self.mi),
                fi, fi.file,
                f"helper {fi.name!r} (traced arguments flow in from jit "
                f"kernel {self.kernel!r})",
                self.kernel, tset, self.visited, self.depth + 1)
            for stmt in fi.node.body:
                sub.visit(stmt)
            self.findings.extend(sub.findings)

    def _check_test(self, test: ast.AST, line: int, kind: str) -> None:
        if self._expr_tainted(test):
            self._flag("TRACER_BRANCH", line,
                       f"Python {kind} on a traced value",
                       "use lax.cond/lax.select/jnp.where or a mask; "
                       "host control flow cannot see device values")

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node.test, node.lineno, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node.test, node.lineno, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node.test, node.lineno, "conditional expression")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range"
                and any(self._expr_tainted(a) for a in it.args)):
            self._flag("TRACER_BRANCH", node.lineno,
                       "range() over a traced value",
                       "use lax.fori_loop/lax.scan for traced trip "
                       "counts")
        self.generic_visit(node)

    # nested defs: their params shadow the outer taint
    def _visit_nested(self, node) -> None:
        params = {a.arg for a in node.args.posonlyargs + node.args.args}
        saved = self.tainted
        self.tainted = saved - params
        self.generic_visit(node)
        self.tainted = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested(node)


def _lint_tracer_leaks(graph: CallGraph, mi: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    visited: Set[Tuple[int, frozenset]] = set()
    for kernel in _collect_kernels(mi):
        fn = kernel.fn
        if isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
            name = "<lambda>"
            body: Sequence[ast.AST] = [fn.body]
        else:
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
            name = fn.name
            body = fn.body
        tainted = params - kernel.static_names - {"self"}
        v = _TaintVisitor(graph, mi, graph.func_of_node(fn), mi.file,
                          f"jit kernel {name!r}", name, tainted, visited)
        for stmt in body:
            v.visit(stmt)
        out.extend(v.findings)
    # two kernels can reach the same helper line — report it once
    seen: Set[Tuple[str, str, int]] = set()
    deduped: List[Finding] = []
    for f in out:
        k = (f.rule, f.file, f.line)
        if k not in seen:
            seen.add(k)
            deduped.append(f)
    return deduped


# -- registry-drift lints ---------------------------------------------------

def _str_arg(node: ast.Call, i: int = 0) -> Optional[str]:
    if len(node.args) > i and isinstance(node.args[i], ast.Constant) \
            and isinstance(node.args[i].value, str):
        return node.args[i].value
    return None


def _is_fire_call(fn: ast.AST) -> bool:
    return ((isinstance(fn, ast.Attribute) and fn.attr == "fire"
             and isinstance(fn.value, ast.Name)
             and fn.value.id == "faults")
            or (isinstance(fn, ast.Name) and fn.id == "fire"))


def _lint_fault_points(mi: ModuleInfo) -> List[Finding]:
    from flink_tpu.faults import KNOWN_FAULT_POINTS

    out: List[Finding] = []
    for node in mi.calls:
        if not _is_fire_call(node.func):
            continue
        point = _str_arg(node)
        if point is not None and point not in KNOWN_FAULT_POINTS:
            out.append(_finding(
                "FAULT_POINT_DRIFT",
                f"faults.fire({point!r}) is not in "
                "faults.KNOWN_FAULT_POINTS — chaos rules targeting it "
                "can never be validated, and the analyzer will reject "
                "confs that name it", mi.file, node.lineno,
                fix="add the point to KNOWN_FAULT_POINTS (and the "
                    "module docstring's point list) or fix the literal"))
    return out


def _lint_unfired_points(graph: CallGraph) -> List[Finding]:
    """Reverse drift: registry entries with NO fire site in the linted
    set. Fire-site resolution: string literals, module constants
    (``fire(TASK_FAULT_POINT)`` / ``fire(mod.CONST)``), and ONE
    parameter-forwarding hop — ``fire(p)`` where ``p`` is a parameter
    of the enclosing function, matched against every call site of a
    function with that name passing a string literal (or module
    constant) in that position/keyword."""
    registry: List[Tuple[str, str, int]] = []
    allow: Set[str] = set()
    reg_present = False
    for mi in graph.modules.values():
        for node in mi.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = {t.id for t in node.targets if isinstance(t, ast.Name)}
            if "KNOWN_FAULT_POINTS" in names:
                reg_present = True
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Constant) \
                            and isinstance(c.value, str):
                        registry.append((c.value, mi.file, c.lineno))
            elif "UNFIRED_ALLOWLIST" in names:
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Constant) \
                            and isinstance(c.value, str):
                        allow.add(c.value)
    if not reg_present:
        return []  # registry not in the linted set — nothing to check

    fired: Set[str] = set()
    param_sites: Dict[Tuple[str, str], FuncInfo] = {}
    for mi in graph.modules.values():
        encl: Optional[Dict[int, FuncInfo]] = None
        for node in mi.calls:
            if not _is_fire_call(node.func):
                continue
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                fired.add(arg.value)
            elif isinstance(arg, ast.Name):
                if arg.id in mi.str_constants:
                    fired.add(mi.str_constants[arg.id])
                else:
                    if encl is None:
                        encl = _enclosing_map(mi)
                    fi = encl.get(id(node))
                    if fi is not None \
                            and arg.id in _all_param_names(fi.node):
                        param_sites[(fi.name, arg.id)] = fi
            elif (isinstance(arg, ast.Attribute)
                  and isinstance(arg.value, ast.Name)):
                tgt = mi.import_aliases.get(arg.value.id)
                if tgt in graph.modules \
                        and arg.attr in graph.modules[tgt].str_constants:
                    fired.add(graph.modules[tgt].str_constants[arg.attr])

    if param_sites:
        for mi in graph.modules.values():
            for node in mi.calls:
                fn = node.func
                cname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                for (fname, pname), fi in param_sites.items():
                    if cname != fname:
                        continue
                    pos = fi.params()
                    offset = 1 if (fi.is_method and pos[:1] == ["self"]
                                   and isinstance(fn, ast.Attribute)) else 0
                    vals: List[ast.AST] = []
                    if pname in pos:
                        i = pos.index(pname) - offset
                        if 0 <= i < len(node.args):
                            vals.append(node.args[i])
                    vals.extend(kw.value for kw in node.keywords
                                if kw.arg == pname)
                    for v in vals:
                        if isinstance(v, ast.Constant) \
                                and isinstance(v.value, str):
                            fired.add(v.value)
                        elif isinstance(v, ast.Name) \
                                and v.id in mi.str_constants:
                            fired.add(mi.str_constants[v.id])

    out: List[Finding] = []
    for point, file, line in registry:
        if point in fired or point in allow:
            continue
        out.append(_finding(
            "FAULT_POINT_UNFIRED",
            f"fault point {point!r} is registered in KNOWN_FAULT_POINTS "
            "but has no faults.fire(...) site anywhere in the linted "
            "tree — dead registry that chaos plans can target but "
            "never hit", file, line,
            fix="instrument the seam with faults.fire, delete the "
                "point, or add it to faults.UNFIRED_ALLOWLIST"))
    return out


def _lint_config_keys(mi: ModuleInfo) -> List[Finding]:
    from flink_tpu.config import is_declared_key

    file = mi.file
    out: List[Finding] = []
    for node in mi.calls:
        fn = node.func
        keys: List[Tuple[str, int]] = []
        if isinstance(fn, ast.Attribute) and fn.attr == "get_raw":
            k = _str_arg(node)
            if k is not None:
                keys.append((k, node.lineno))
        elif (isinstance(fn, (ast.Name, ast.Attribute))
              and (fn.attr if isinstance(fn, ast.Attribute) else fn.id)
              == "Configuration" and node.args
              and isinstance(node.args[0], ast.Dict)):
            for kn in node.args[0].keys:
                if isinstance(kn, ast.Constant) and isinstance(kn.value, str):
                    keys.append((kn.value, kn.lineno))
        for key, line in keys:
            if not is_declared_key(key):
                out.append(_finding(
                    "CONFIG_KEY_DRIFT",
                    f"config key {key!r} is outside the declared option "
                    "grammar — the runtime ignores it", file, line,
                    fix="declare a ConfigOption (or dynamic prefix) in "
                        "config.py, or fix the literal"))
    return out


def _option_decls(mi: ModuleInfo) -> List[Tuple[str, str, int]]:
    """(key, file, line) of every ConfigOption/duration_option literal."""
    decls = []
    for node in mi.calls:
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name in ("ConfigOption", "duration_option"):
            key = _str_arg(node)
            if key is not None:
                decls.append((key, mi.file, node.lineno))
    return decls


def _lint_metric_names(mi: ModuleInfo) -> List[Finding]:
    file = mi.file
    out: List[Finding] = []
    for node in mi.calls:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        names: List[str] = []
        if fn.attr in _METRIC_KINDS:
            n = _str_arg(node)
            if n is not None:
                names.append(n)
        elif fn.attr == "group":
            names.extend(
                a.value for a in node.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str))
        for n in names:
            if not _METRIC_NAME_RE.match(n):
                out.append(_finding(
                    "METRIC_NAME_INVALID",
                    f"metric name {n!r} is outside the snake_case "
                    "grammar ([a-z0-9_] dotted segments) dashboards "
                    "key on", file, node.lineno,
                    fix="rename to lowercase snake_case"))
    return out


# -- durability-seam lint ---------------------------------------------------

def _lint_durability(mi: ModuleInfo) -> List[Finding]:
    """Raw durable-write constructs in the PR-14 durable tiers: every
    write must route through fs.py (open_write sync, fs.fsync,
    fs.rename, write_atomic) so CrashFS recording and the ENOSPC
    policy cover it. Allowed residue: os.open(O_CREAT|O_EXCL) +
    os.fdopen lock primitives, and os.rename of lock/lease -> grave
    files (local-lock bookkeeping, never durable payload)."""
    file = mi.file
    norm = file.replace("\\", "/")
    if norm not in DURABLE_MODULES:
        return []
    out: List[Finding] = []
    for node in mi.calls:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "open":
            mode = ""
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if "w" in mode or "a" in mode or "+" in mode:
                out.append(_finding(
                    "DURABILITY_SEAM_BYPASS",
                    f"raw open(..., {mode!r}) in durable module {norm} "
                    "bypasses the fs.py seam — no CrashFS recording, no "
                    "ENOSPC policy, silently re-opens the power-cut "
                    "hole the crash explorer verifies closed",
                    file, node.lineno,
                    fix="route through fs.open_write(sync=) / "
                        "fs.write_atomic"))
        elif (isinstance(fn, ast.Attribute)
              and isinstance(fn.value, ast.Name) and fn.value.id == "os"
              and fn.attr in ("fsync", "replace", "rename")):
            if fn.attr == "rename":
                text = " ".join(_unparse(a) for a in node.args).lower()
                if any(t in text for t in ("lock", "lease", "grave")):
                    continue  # documented local-lock-primitive residue
            out.append(_finding(
                "DURABILITY_SEAM_BYPASS",
                f"os.{fn.attr}(...) in durable module {norm} bypasses "
                "the fs.py seam — no CrashFS recording, no ENOSPC "
                "policy", file, node.lineno,
                fix="route through fs.fsync / fs.rename / write_atomic"))
    return out


# -- concurrency lint: shared writes in HostPool.run_tasks closures ---------

def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name of an Attribute/Subscript chain (``self`` of
    ``self.panes[p]``), or None when the base is not a plain name."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _lock_guarded_expr(node: ast.AST) -> bool:
    """Legacy name-substring lock marker (any Name or attribute segment
    containing 'lock', case-insensitive) — kept for locks that arrive
    as parameters, where no binding is visible. The binding-type check
    (CallGraph.is_lock_expr) is the primary mechanism."""
    for c in ast.walk(node):
        if isinstance(c, ast.Name) and "lock" in c.id.lower():
            return True
        if isinstance(c, ast.Attribute) and "lock" in c.attr.lower():
            return True
    return False


def _local_locks(fn: ast.AST) -> Set[str]:
    """Names this function body binds to a Lock/RLock/... constructor."""
    out: Set[str] = set()
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    for stmt in body:
        for c in ast.walk(stmt):
            if isinstance(c, ast.Assign) \
                    and _call_ctor_name(c.value) in LOCK_CONSTRUCTORS:
                out.update(t.id for t in c.targets
                           if isinstance(t, ast.Name))
    return out


class _SharedWriteVisitor(ast.NodeVisitor):
    """Walk one task closure's body: flag Assign/AugAssign whose target
    routes through a FREE variable (not a parameter, not a local)
    unless the statement sits under a with-lock guard. Resolvable
    same-module calls are followed to any depth; a formal bound to a
    shared actual (including the implicit ``self`` receiver) keeps the
    shared tag in the callee."""

    def __init__(self, graph: CallGraph, mi: ModuleInfo,
                 ctx: Optional[FuncInfo], file: str, closure_name: str,
                 local_names: Set[str], local_locks: Set[str],
                 visited: Set, shared: Optional[Set[str]] = None,
                 lock_depth: int = 0, depth: int = 0) -> None:
        self.graph = graph
        self.mi = mi
        self.ctx = ctx
        self.file = file
        self.closure = closure_name
        self.locals = set(local_names)
        # formals bound to shared actuals at the call site: rebinding
        # one is a harmless local rebind, but mutating THROUGH it
        # (attribute/subscript store) reaches the caller's object
        self.shared = set(shared or ())
        self.local_locks = set(local_locks)
        self.visited = visited
        self.lock_depth = lock_depth
        self.depth = depth
        self.findings: List[Finding] = []

    def _shared_root(self, name: str) -> bool:
        return name in self.shared or name not in self.locals

    def _flag(self, line: int, target_src: str) -> None:
        self.findings.append(_finding(
            "HOSTPOOL_SHARED_WRITE",
            f"task closure {self.closure!r} writes shared state "
            f"({target_src}) without a lock — run_tasks executes it on "
            "a pool worker thread; unguarded read-modify-writes lose "
            "updates (the obs/metrics.py Counter race class)",
            self.file, line,
            fix="guard the write with a `with <lock>:` block, or "
                "return a partial and combine on the caller (results "
                "arrive in submission order)"))

    def _check_target(self, target: ast.AST, line: int) -> None:
        if self.lock_depth > 0:
            return
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            root = _root_name(target)
            if root is not None and self._shared_root(root):
                self._flag(line, _unparse(target) or "<target>")
        elif isinstance(target, ast.Name):
            # a bare-name write is local unless declared otherwise
            # (visit_Nonlocal/Global remove such names from `locals`)
            if target.id not in self.locals:
                self._flag(line, target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_target(el, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self.locals.difference_update(node.names)

    def visit_Global(self, node: ast.Global) -> None:
        self.locals.difference_update(node.names)

    def _guarded(self, expr: ast.AST) -> bool:
        return (_lock_guarded_expr(expr)
                or self.graph.is_lock_expr(expr, self.ctx,
                                           self.local_locks, self.mi))

    def _visit_with(self, node) -> None:
        guarded = any(self._guarded(i.context_expr) for i in node.items)
        if guarded:
            self.lock_depth += 1
        self.generic_visit(node)
        if guarded:
            self.lock_depth -= 1

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._descend(node)
        self.generic_visit(node)

    def _descend(self, node: ast.Call) -> None:
        if self.depth >= _POOL_DEPTH:
            return
        for fi in self.graph.resolve(node, self.ctx, self.mi):
            if fi.module != self.mi.name:
                continue  # same-module discipline only
            pos = fi.params()
            offset = 1 if (fi.is_method and pos[:1] == ["self"]
                           and isinstance(node.func, ast.Attribute)) else 0
            shared: Set[str] = set()
            if offset == 1:
                r = _root_name(node.func.value)
                if r is not None and self._shared_root(r):
                    shared.add("self")
            for i, arg in enumerate(node.args):
                j = i + offset
                if j >= len(pos):
                    break
                if isinstance(arg, (ast.Name, ast.Attribute,
                                    ast.Subscript)):
                    r = _root_name(arg)
                    if r is not None and self._shared_root(r):
                        shared.add(pos[j])
            names = set(_all_param_names(fi.node))
            for kw in node.keywords:
                if kw.arg and kw.arg in names and isinstance(
                        kw.value, (ast.Name, ast.Attribute, ast.Subscript)):
                    r = _root_name(kw.value)
                    if r is not None and self._shared_root(r):
                        shared.add(kw.arg)
            key = (id(fi.node), frozenset(shared), self.lock_depth > 0)
            if key in self.visited:
                continue
            self.visited.add(key)
            sub = _SharedWriteVisitor(
                self.graph, self.mi, fi, fi.file,
                f"{self.closure} -> {fi.name}",
                _fn_locals(fi.node), _local_locks(fi.node),
                self.visited, shared=shared,
                lock_depth=1 if self.lock_depth > 0 else 0,
                depth=self.depth + 1)
            for stmt in fi.node.body:
                sub.visit(stmt)
            self.findings.extend(sub.findings)

    # nested defs/lambdas get their own scope; don't descend into their
    # bodies here (a nested def submitted separately is its own root)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


def _fn_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _binding_names(t: ast.AST) -> Iterator[str]:
    """Names a binding target introduces — Name / Tuple / List /
    Starred structure only, so ``d[k], x = ...`` yields ``x`` but not
    ``d`` or ``k`` (a subscript store mutates, it doesn't bind)."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _binding_names(e)
    elif isinstance(t, ast.Starred):
        yield from _binding_names(t.value)


def _fn_locals(fn: ast.AST) -> Set[str]:
    """Parameters + bare names the body binds (assignments, for/with
    targets, comprehension-free walk at this scope)."""
    names = _fn_params(fn)
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    for stmt in body:
        for c in ast.walk(stmt):
            if isinstance(c, ast.Assign):
                for t in c.targets:
                    names.update(_binding_names(t))
            elif isinstance(c, (ast.AnnAssign, ast.AugAssign,
                                ast.NamedExpr)):
                # `n: int = 0`, `n += 1` (local unless nonlocal/global
                # — the visitor re-frees declared names), `(n := ...)`
                if isinstance(c.target, ast.Name):
                    names.add(c.target.id)
            elif isinstance(c, (ast.For, ast.AsyncFor)):
                names.update(_binding_names(c.target))
            elif isinstance(c, (ast.With, ast.AsyncWith)):
                for i in c.items:
                    if i.optional_vars is not None:
                        names.update(_binding_names(i.optional_vars))
    return names


def _lint_hostpool_writes(graph: CallGraph,
                          mi: ModuleInfo) -> List[Finding]:
    tree, file = mi.tree, mi.file
    defs_by_name: Dict[str, List[ast.AST]] = {
        name: [fi.node for fi in fns]
        for name, fns in mi.functions.items()}

    # name → closures the file binds into it (list/tuple literals,
    # listcomp values, .append(lambda ...) / .append(local_def)) —
    # resolves `run_tasks(tasks)`. Name references resolve to local
    # defs only where the expression IS the closure (a bare name, a
    # literal element, a comprehension elt) — resolving every Name in
    # an arbitrary value would mis-tag caller-thread helpers as tasks.
    bound: Dict[str, List[ast.AST]] = {}

    def closures_in(expr: ast.AST) -> List[ast.AST]:
        out = [c for c in ast.walk(expr) if isinstance(c, ast.Lambda)]
        names: List[str] = []
        if isinstance(expr, ast.Name):
            names = [expr.id]
        elif isinstance(expr, (ast.List, ast.Tuple)):
            names = [e.id for e in expr.elts if isinstance(e, ast.Name)]
        elif isinstance(expr, (ast.ListComp, ast.GeneratorExp)) \
                and isinstance(expr.elt, ast.Name):
            names = [expr.elt.id]
        for nm in names:
            out.extend(bound.get(nm, ()))
            out.extend(defs_by_name.get(nm, ()))
        return out

    for node in mi.nodes:
        if isinstance(node, ast.Assign):
            closures = closures_in(node.value)
            if closures:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        bound.setdefault(t.id, []).extend(closures)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "append"
              and isinstance(node.func.value, ast.Name)):
            for a in node.args:
                bound.setdefault(node.func.value.id, []).extend(
                    closures_in(a))

    encl: Optional[Dict[int, FuncInfo]] = None

    def ctx_for(fn: ast.AST) -> Optional[FuncInfo]:
        """The closure's own FuncInfo (nested defs carry their class
        tag), else the innermost enclosing function (lambdas)."""
        nonlocal encl
        fi = graph.func_of_node(fn)
        if fi is not None:
            return fi
        if encl is None:
            encl = _enclosing_map(mi)
        return encl.get(id(fn))

    out: List[Finding] = []
    visited: Set = set()
    for node in mi.calls:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "run_tasks"):
            continue
        closures: List[ast.AST] = []
        for a in node.args:
            closures.extend(closures_in(a))
        for fn in closures:
            key = (id(fn), "root")
            if key in visited:
                continue
            visited.add(key)
            name = getattr(fn, "name", "<lambda>")
            v = _SharedWriteVisitor(graph, mi, ctx_for(fn), file, name,
                                    _fn_locals(fn), _local_locks(fn),
                                    visited)
            body = ([fn.body] if isinstance(fn, ast.Lambda)
                    else fn.body)
            for stmt in body:
                v.visit(stmt)
            out.extend(v.findings)
    return out


# -- lock-order lint --------------------------------------------------------

def _lint_lock_order(graph: CallGraph) -> List[Finding]:
    """Build the lock-acquisition-order graph: an edge A -> B when some
    path acquires tracked lock B while holding A — directly nested
    ``with`` guards, or a call made under A whose (transitive) callee
    acquires B. A 2-cycle (A -> B and B -> A) is a potential ABBA
    deadlock; the finding names both acquisition paths. Nested defs/
    lambdas are excluded from their encloser's walk (they run in
    another frame), and self-edges (RLock reentrancy) are not edges."""
    memo: Dict[int, Dict[str, str]] = {}

    def acquires(fi: FuncInfo, seen: frozenset) -> Dict[str, str]:
        """Transitive lock-id -> witness-path summary for one function."""
        key = id(fi.node)
        if key in memo:
            return memo[key]
        if key in seen or len(seen) > 16:
            return {}
        seen2 = seen | {key}
        out: Dict[str, str] = {}
        for node in _iter_skip_nested(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for i in node.items:
                    lid = graph.lock_id(i.context_expr, fi)
                    if lid:
                        out.setdefault(
                            lid, f"{fi.file}:{node.lineno} in {fi.qname}")
            elif isinstance(node, ast.Call):
                for callee in graph.resolve(node, fi):
                    for lid, w in acquires(callee, seen2).items():
                        out.setdefault(
                            lid, f"{fi.file}:{node.lineno} in "
                                 f"{fi.qname} -> {w}")
        memo[key] = out
        return out

    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def record(held: List[Tuple[str, str]], lid: str,
               file: str, line: int, via: str) -> None:
        for h, hw in held:
            if h != lid:  # reentrant self-acquire (RLock) is not an edge
                edges.setdefault((h, lid), (file, line,
                                            f"{hw}, then {via}"))

    def visit(fi: FuncInfo, node: ast.AST,
              held: List[Tuple[str, str]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fi.node:
            return  # another frame/thread
        if isinstance(node, (ast.With, ast.AsyncWith)):
            lids = [lid for i in node.items
                    for lid in [graph.lock_id(i.context_expr, fi)] if lid]
            site = f"{fi.file}:{node.lineno} in {fi.qname}"
            for lid in lids:
                record(held, lid, fi.file, node.lineno,
                       f"{lid} at {site}")
            held = held + [(lid, f"{lid} at {site}") for lid in lids]
        elif isinstance(node, ast.Call) and held:
            for callee in graph.resolve(node, fi):
                for lid, w in acquires(callee, frozenset()).items():
                    record(held, lid, fi.file, node.lineno,
                           f"{lid} via the call at {fi.file}:"
                           f"{node.lineno} in {fi.qname} -> {w}")
        for c in ast.iter_child_nodes(node):
            visit(fi, c, held)

    def module_has_tracked_with(mi: ModuleInfo) -> bool:
        """Can any `with` in this module acquire a TRACKED lock? held
        stacks only grow from such withs in a function's own frame, so
        a module without one cannot originate a lock-order edge and
        its functions need no visit (callees elsewhere are reached via
        the `acquires` summaries on demand)."""
        lock_attrs: Set[str] = set()
        for ci in mi.classes.values():
            lock_attrs |= ci.lock_attrs
        for w in mi.withs:
            for i in w.items:
                e = i.context_expr
                if isinstance(e, ast.Name) and e.id in mi.lock_names:
                    return True
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr in lock_attrs):
                    return True
        return False

    for mi in graph.modules.values():
        if not module_has_tracked_with(mi):
            continue
        for fns in mi.functions.values():
            for fi in fns:
                # skip functions with no `with` in their own subtree —
                # they can never build a held stack
                if any(isinstance(n, (ast.With, ast.AsyncWith))
                       for n in ast.walk(fi.node)):
                    visit(fi, fi.node, [])

    out: List[Finding] = []
    for (a, b) in sorted(edges):
        if a >= b or (b, a) not in edges:
            continue
        file, line, desc = edges[(a, b)]
        _rf, _rl, rdesc = edges[(b, a)]
        out.append(_finding(
            "LOCK_ORDER_CYCLE",
            f"lock-order cycle between {a} and {b}: one path acquires "
            f"{desc}; the opposite path acquires {rdesc} — two threads "
            "interleaving these paths deadlock", file, line,
            fix="pick one global acquisition order for these locks "
                "(lock hierarchy) or collapse them into one lock"))
    return out


# -- fencing lint -----------------------------------------------------------

def _is_fence_call(fn: ast.AST) -> bool:
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    return "verify" in name.lower() or name == "renew"


def _publish_call_name(fn: ast.AST) -> str:
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    if name == "put_if" or name.endswith("write_atomic"):
        return name
    return ""


def _is_leased_class(ci) -> bool:
    """A class whose methods call ``self.<attr>.verify(...)`` — the
    syntactic signature of holding an epoch-fenced lease (detected at
    index time, see callgraph ClassInfo.leased)."""
    return ci.leased


def _lint_fence_publish(graph: CallGraph) -> List[Finding]:
    """For every PUBLIC method of a leased class, walk statements in
    source order threading a verified-flag through resolved calls: a
    fence call (``*verify*``/``renew``) sets it; a
    ``write_atomic``/``put_if`` whose argument text (with one hop of
    local-variable substitution) names a fenced record while the flag
    is unset is a publication a deposed leaseholder could make after
    takeover. Publishing the lease/lock record itself IS the fence
    mechanism and is exempt."""
    out: List[Finding] = []
    memo: Dict[Tuple[int, bool], bool] = {}

    def walk(fi: FuncInfo, state: bool, origin: str, depth: int) -> bool:
        key = (id(fi.node), state)
        if key in memo or depth > _FENCE_DEPTH:
            return memo.get(key, state)
        memo[key] = state  # provisional (recursion guard)
        env: Dict[str, str] = {}
        for node in _iter_skip_nested(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                env[node.targets[0].id] = _unparse(node.value).lower()
            elif isinstance(node, ast.Call):
                fn = node.func
                if _is_fence_call(fn):
                    state = True
                    continue
                if _publish_call_name(fn):
                    texts = []
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        texts.append(_unparse(a).lower())
                        if isinstance(a, ast.Name) and a.id in env:
                            texts.append(env[a.id])
                    text = " ".join(texts)
                    if "lease" in text or "lock" in text:
                        continue  # the lease/lock record IS the fence
                    tokens = [t for t in _FENCED_TOKENS if t in text]
                    if tokens and not state:
                        out.append(_finding(
                            "FENCE_UNVERIFIED_PUBLISH",
                            f"{origin} reaches a "
                            f"{'/'.join(tokens)}-record publication in "
                            f"{fi.qname} with no lease verify()/renew "
                            "on the path — a deposed leaseholder could "
                            "publish after takeover", fi.file,
                            node.lineno,
                            fix="call the lease verify()/renew gate "
                                "before this publication"))
                    continue
                for callee in graph.resolve(node, fi):
                    state = walk(callee, state, origin, depth + 1)
        memo[key] = state
        return state

    for mi in graph.modules.values():
        for ci in mi.classes.values():
            if not _is_leased_class(ci):
                continue
            for name, fi in sorted(ci.methods.items()):
                if name.startswith("_"):
                    continue  # helpers inherit state from their callers
                walk(fi, False, f"leased {ci.name}.{name}()", 0)
    return out


# -- entry points -----------------------------------------------------------

def _lint_graph(graph: CallGraph) -> List[Finding]:
    """Every rule over one indexed module set (the per-file rules plus
    the interprocedural planes), deduplicated and sorted."""
    out: List[Finding] = []
    for mi in graph.modules.values():
        out.extend(_lint_tracer_leaks(graph, mi))
        out.extend(_lint_fault_points(mi))
        out.extend(_lint_config_keys(mi))
        out.extend(_lint_metric_names(mi))
        out.extend(_lint_hostpool_writes(graph, mi))
        out.extend(_lint_durability(mi))
    out.extend(_lint_lock_order(graph))
    out.extend(_lint_fence_publish(graph))
    out.extend(_lint_unfired_points(graph))
    seen: Set[Tuple[str, str, int, str]] = set()
    deduped: List[Finding] = []
    for f in out:
        k = (f.rule, f.file, f.line, f.message)
        if k not in seen:
            seen.add(k)
            deduped.append(f)
    deduped.sort(key=lambda f: (f.file, f.line, f.rule))
    return deduped


def lint_source(source: str, file: str) -> List[Finding]:
    """Lint one file's source text (the unit every test fixture uses).
    The file becomes a single-module call graph, so the
    interprocedural rules run within it; pass a durable-module relpath
    as ``file`` to exercise the durability plane."""
    tree = ast.parse(source, filename=file)
    graph = build_graph({file.replace("\\", "/"): tree})
    return _lint_graph(graph)


def repo_root() -> str:
    """The directory holding the flink_tpu package (lint path base)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


DEFAULT_LINT_PATHS = ("flink_tpu", "tools", "bench.py", "bench_micro.py")


def lint_paths(paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Lint every ``*.py`` under ``paths`` (files or directories,
    resolved against ``root`` — defaults to the shipped tree) as ONE
    call graph, so cross-module call edges resolve. Also runs the
    cross-file CONFIG_OPTION_DUP check over the whole set."""
    from flink_tpu.analysis.plan_rules import load_option_grammar

    load_option_grammar()
    root = root or repo_root()
    files: List[str] = []
    for p in (paths or DEFAULT_LINT_PATHS):
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full) and not os.path.isabs(p):
            full = os.path.abspath(p)  # CLI arg relative to the cwd
        if os.path.isfile(full):
            files.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, fnames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(fnames) if f.endswith(".py"))
        else:
            # a typo'd path silently linting NOTHING would leave a CI
            # drift gate green while checking nothing — fail loudly
            raise ValueError(f"lint path does not exist: {p!r} "
                             f"(resolved against {root!r} and the cwd)")
    trees: Dict[str, ast.Module] = {}
    for f in sorted(set(files)):
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        trees[rel] = ast.parse(src, filename=rel)
    graph = build_graph(trees)
    out = _lint_graph(graph)
    decls: List[Tuple[str, str, int]] = []
    for mi in graph.modules.values():
        decls.extend(_option_decls(mi))
    by_key: Dict[str, List[Tuple[str, str, int]]] = {}
    for key, file, line in decls:
        by_key.setdefault(key, []).append((key, file, line))
    for key, sites in sorted(by_key.items()):
        if len(sites) > 1:
            sites.sort(key=lambda s: (s[1], s[2]))
            first = f"{sites[0][1]}:{sites[0][2]}"
            for _, file, line in sites[1:]:
                out.append(_finding(
                    "CONFIG_OPTION_DUP",
                    f"option key {key!r} already declared at {first} — "
                    "re-declaration silently replaces it in the "
                    "registry", file, line,
                    fix="reuse the existing ConfigOption constant"))
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out
