"""Dataflow plane of the plan analyzer — abstract interpretation over
the lowered ExecutionPlan.

PR 4's rule engine is linear: every rule sees one node at a time, so a
keyBy on a field the upstream map dropped, a GlobalWindow whose state
grows without bound, or a join leg whose watermark can never advance
all still fail at runtime, after records flow. This module adds the
second plane: ONE topological walk (`propagate`) that interprets the
plan edge-by-edge over three lattices, with the registered dataflow
rules (FIELD_NOT_IN_SCHEMA, SCHEMA_MISMATCH_UNION,
UNBOUNDED_STATE_GROWTH, STALLED_WATERMARK_LEG, NON_TXN_SINK_IN_CHAIN,
STATE_BYTES_EXCEEDED, CHANGELOG_SINK_MISMATCH) reading the propagated
facts — the
graph-compilation-time validation role of the reference's
Transformation → StreamGraph translation (PAPER §2 layer L6), extended
with the state/time facts the multi-tenant admission path needs.

The three lattices:

- **Record schema** — field name → numpy dtype name; ``None`` is the
  lattice top (unknown). Seeded from source declarations
  (``Source.declared_schema``), stepped per op: stateful operators use
  the compiler-recorded ``ExecNode.out_schema`` (the fired-row shape is
  a plan fact); chains are ABSTRACTLY EVALUATED by running their fused
  fns on an EMPTY typed batch (0 rows of the inferred dtypes — the
  dask-style meta-inference trick: dtype/field propagation is exact,
  no data ever flows, and a KeyError IS the field-reference error the
  rule reports). Any other failure degrades the schema to unknown —
  never a finding.
- **State-growth bound** — stateless | bounded | unbounded | opaque,
  with a human-readable shape (keys × live panes, live session spans,
  partial matches) and, for the dense lane layouts, a BYTES-PER-KEY
  estimate derived from the window/lateness geometry — the number
  ``analyze --explain`` prints and ``analysis.max-state-bytes-per-key``
  budgets against. Derived from assigner type, trigger/evictor
  discipline, session gap, and CEP skip strategy.
- **Watermark capability** — which time axis a node's output rows
  carry: ``event`` (event-time watermark meaningful and advancing),
  ``processing`` (proc-time assigners — rows stamped off the operator
  clock), or ``none`` (count/global windows — no time axis at all).
  The pipeline watermark is computed from SOURCE event timestamps
  (time/watermarks.py), so an event-time operator fed by a
  ``processing``/``none`` leg assigns panes the source watermark can
  never meaningfully cross — the stalled-leg shape.

Chain evaluation and side effects: user fns are only ever CALLED on the
explicit analysis surfaces (``env.analyze()`` / `flink_tpu analyze`);
the driver's automatic submit pass runs with chain evaluation OFF
(core.analyze ``eval_chains=False``), so a side-effecting map never
observes a phantom batch just because the job was submitted.

Honest scope: no cross-function taint (a field smuggled through opaque
state is invisible), no symbolic shapes (bytes estimates use the
declared config geometry, not data), and schema facts stop at the
first chain that raises on an empty batch.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import warnings
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.analysis.core import Finding, plan_rule

Schema = Optional[Dict[str, str]]  # field -> numpy dtype name; None = top

# rules this walk can emit findings for during propagation
_WALK_RULES = ("FIELD_NOT_IN_SCHEMA", "SCHEMA_MISMATCH_UNION")

# chain-evaluation mode, set by core.analyze around the rule loop.
# THREAD-LOCAL: a driver submit pass (eval off) and an explicit
# env.analyze() (eval on) may run on different threads concurrently —
# a module global would let one flip the other's mode mid-loop and
# break the never-call-user-fns-at-submit guarantee.
_STATE = threading.local()


def _eval_chains_enabled() -> bool:
    return getattr(_STATE, "eval_chains", True)


@contextlib.contextmanager
def chain_eval_mode(enabled: bool):
    prev = _eval_chains_enabled()
    _STATE.eval_chains = bool(enabled)
    try:
        yield
    finally:
        _STATE.eval_chains = prev


def _f(message: str, fix: str = "", node=None, node_name: str = "") -> Finding:
    # analyze() stamps the registered rule id + severity
    return Finding(rule="", severity="warn", message=message, fix=fix,
                   node=node, node_name=node_name)


@dataclasses.dataclass
class NodeFacts:
    """The propagated facts of one ExecNode — what `analyze --explain`
    prints and the dataflow rules read."""

    node_id: int
    kind: str
    name: str
    in_schema: Schema = None
    schema: Schema = None          # output schema
    schema_note: str = ""
    state: str = "stateless"       # stateless|bounded|unbounded|opaque
    state_detail: str = ""
    state_bytes_per_key: Optional[int] = None
    wm: str = "event"              # event|processing|none
    wm_note: str = ""
    log_tainted: bool = False      # downstream of a LogSource
    bounded_input: bool = True     # every upstream source is bounded
    # changelog axis: output rows are op-typed (records.OP_FIELD) — set
    # at retract-mode operators, carried through pass-through nodes,
    # reset at re-aggregating operators (their fired rows are fresh)
    changelog: bool = False


@dataclasses.dataclass
class PlanFacts:
    nodes: Dict[int, NodeFacts]
    upstream: Dict[int, List[int]]
    findings: Dict[str, List[Finding]]


# -- memo: every dataflow rule reads one propagation per analyze() call
# (thread-local, like the eval mode: concurrent analyses must not see
# each other's plans)

def propagate(plan, config) -> PlanFacts:
    """One topological walk over (plan, config); memoized on identity so
    the six dataflow rules share a single interpretation."""
    memo = getattr(_STATE, "memo", None)
    mode = _eval_chains_enabled()
    if (memo is not None and memo[0] is plan and memo[1] is config
            and memo[2] == mode):
        return memo[3]
    facts = _propagate(plan, config)
    _STATE.memo = (plan, config, mode, facts)
    return facts


def clear_memo() -> None:
    """Drop this thread's propagation memo (tests measuring a fresh
    submit-shaped pass use this)."""
    _STATE.memo = None


# -- schema plane -----------------------------------------------------------

def _source_schema(source) -> Schema:
    try:
        s = source.declared_schema()
    except Exception:
        return None
    if not isinstance(s, dict) or not s:
        return None
    return {str(k): str(v) for k, v in s.items()}


def _empty_batch(schema: Dict[str, str]):
    data = {f: np.zeros((0,), dtype=np.dtype(dt))
            for f, dt in schema.items()}
    return data, np.zeros((0,), np.int64), np.zeros((0,), bool)


def _eval_chain(nf: NodeFacts, fns, schema: Dict[str, str],
                out: Dict[str, List[Finding]]) -> Schema:
    """Abstractly evaluate a chain's fused fns on an EMPTY typed batch.
    A KeyError with a string key is exactly the field-reference error
    FIELD_NOT_IN_SCHEMA exists for; anything else degrades to unknown
    (the fn is opaque to this analysis, not wrong)."""
    data, ts, valid = _empty_batch(schema)
    for i, fn in enumerate(fns):
        known = sorted(data)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with np.errstate(all="ignore"):
                    data, ts, valid = fn(data, ts, valid)
            data = {str(k): np.asarray(v) for k, v in dict(data).items()}
        except KeyError as e:
            missing = e.args[0] if e.args else "?"
            # only a STRING key ABSENT from the input schema is a
            # field-reference error; a KeyError whose key IS in the
            # schema came from some other dict inside the fn (a
            # runtime-populated lookup table) — that fn is opaque to
            # this analysis, not wrong
            if isinstance(missing, str) and missing not in known:
                out["FIELD_NOT_IN_SCHEMA"].append(_f(
                    f"chain {nf.name!r} (fn {i}) references field "
                    f"{missing!r}, which is not in its input schema "
                    f"{known} — this map/filter raises KeyError on the "
                    "first batch",
                    fix="emit the field upstream (or fix the name); "
                        "`analyze --explain` prints each node's "
                        "inferred schema",
                    node=nf.node_id, node_name=nf.name))
                nf.schema_note = f"fn {i} references missing {missing!r}"
            else:
                nf.schema_note = f"fn {i} raised KeyError({missing!r})"
            return None
        except Exception as e:
            nf.schema_note = (f"fn {i} opaque to abstract eval "
                              f"({type(e).__name__})")
            return None
    return {k: str(v.dtype) for k, v in data.items()}


def _check_fields(nf: NodeFacts, schema: Schema, fields, what: str,
                  out: Dict[str, List[Finding]]) -> None:
    """FIELD_NOT_IN_SCHEMA for declared op field references (key
    columns, aggregate input lanes, join keys) against a KNOWN input
    schema. Unknown schema = no finding (conservative)."""
    if schema is None:
        return
    for f in fields:
        if f and f not in schema:
            out["FIELD_NOT_IN_SCHEMA"].append(_f(
                f"{nf.kind} {nf.name!r} {what} {f!r}, but the upstream "
                f"schema is {sorted(schema)} — the field was dropped or "
                "renamed before this operator",
                fix="carry the field through the upstream maps, or fix "
                    "the reference; `analyze --explain` prints each "
                    "node's inferred schema",
                node=nf.node_id, node_name=nf.name))


# -- state plane ------------------------------------------------------------

def _lane_bytes(agg) -> int:
    """Per-(key, cell) accumulator footprint of the dense lane layout:
    f32 sum/max/min lanes + the always-present i64 count lane."""
    return (agg.sum_width + agg.max_width + agg.min_width) * 4 + 8


def _is_purging(trigger) -> bool:
    from flink_tpu.api.windowing import PurgingTrigger

    return isinstance(trigger, PurgingTrigger)


def _state_facts(node, config) -> Tuple[str, str, Optional[int]]:
    """(bound, detail, bytes_per_key) for one stateful node — window
    type, trigger/evictor discipline, session gap, and CEP skip
    strategy decide the bound; the dense layouts get a bytes estimate
    from the window/lateness geometry."""
    from flink_tpu.api.windowing import GlobalWindows

    wt = node.window_transform
    kind = node.kind
    if kind in ("window", "window_all"):
        assigner = getattr(wt, "assigner", None)
        lat = int(getattr(wt, "allowed_lateness_ms", 0))
        if isinstance(assigner, GlobalWindows):
            trig = getattr(wt, "trigger", None)
            if trig is None:
                return ("unbounded",
                        "GlobalWindows with no trigger: every record is "
                        "state forever", None)
            if _is_purging(trig):
                return ("bounded", "GlobalWindows purged at every fire",
                        _lane_bytes(wt.aggregate))
            return ("unbounded",
                    f"GlobalWindows with non-purging "
                    f"{type(trig).__name__}: accumulators are never "
                    "cleared", None)
        pane = int(assigner.pane_ms)
        live = (int(assigner.size_ms) + lat + pane - 1) // pane + 1
        per = _lane_bytes(wt.aggregate)
        return ("bounded",
                f"keys × {live} live panes (window {assigner.size_ms}ms"
                f" + lateness {lat}ms / pane {pane}ms), "
                f"{per} B per (key, pane) cell", per * live)
    if kind == "evicting_window":
        assigner = getattr(wt, "assigner", None)
        trig = getattr(wt, "trigger", None)
        if isinstance(assigner, GlobalWindows) and not _is_purging(trig) \
                and getattr(wt, "evictor", None) is None:
            return ("unbounded",
                    "GlobalWindows element buffer with a non-purging "
                    f"trigger ({type(trig).__name__ if trig else 'none'})"
                    " and no evictor: the buffer retains every element "
                    "forever", None)
        return ("bounded",
                "element buffer within window lifetime + lateness "
                "(bytes are data-dependent)", None)
    if kind == "count_window":
        if getattr(wt, "purge", True):
            return ("bounded",
                    f"one accumulator per key, purged every "
                    f"{getattr(wt, 'size', '?')} elements",
                    _lane_bytes(wt.aggregate))
        return ("unbounded",
                "count window without purge: accumulators never reset",
                None)
    if kind == "session":
        per = _lane_bytes(wt.aggregate) + 24  # + key/start/last i64
        return ("bounded",
                f"live spans expire at the watermark horizon (gap "
                f"{wt.gap_ms}ms + lateness "
                f"{getattr(wt, 'allowed_lateness_ms', 0)}ms), "
                f"{per} B per span", per)
    if kind == "global_agg":
        return ("bounded",
                "one accumulator per key, never expires — bounded by "
                "key cardinality (state.num-key-shards × "
                "state.slots-per-shard)", _lane_bytes(wt.aggregate))
    if kind == "join":
        return ("bounded",
                "both sides buffered within window lifetime + lateness "
                "(bytes are data-dependent)", None)
    if kind == "cep":
        pattern = getattr(wt, "pattern", None)
        mode = getattr(pattern, "after_match_mode", "SKIP_PAST_LAST_EVENT")
        stages = getattr(pattern, "stages", None) or ()
        detail = (f"partial-match state per key "
                  f"({len(stages) or '?'} stages, {mode})")
        if mode == "NO_SKIP":
            detail += (" — bounded overflow-checked buffer of "
                       "overlapping partial matches")
        return ("bounded", detail, None)
    if kind == "async_io":
        return ("bounded",
                f"≤ {getattr(wt, 'capacity', '?')} in-flight batches",
                None)
    if kind == "process":
        return ("opaque", "user-managed keyed state + timers", None)
    if kind == "broadcast_connect":
        return ("opaque", "user-managed broadcast state", None)
    return ("stateless", "", None)


# -- watermark plane --------------------------------------------------------

def _wm_facts(node, in_wm: List[str]) -> Tuple[str, str]:
    """(axis, note) of a node's OUTPUT rows. The stepping rules follow
    the driver's fired-row forwarding: downstream ts is ``__ts__`` if
    the op emits one, else ``window_end - 1`` (runtime/driver.py
    _emit_fired) — so the axis is the op's window axis."""
    from flink_tpu.api.windowing import GlobalWindows

    kind = node.kind
    if kind == "source":
        s = node.watermark_strategy
        if s is None:
            return "event", "default monotonous clock"
        note = f"bounded-out-of-orderness {s.max_out_of_orderness_ms}ms"
        if s.idleness_ms is not None:
            note += f", idle after {s.idleness_ms}ms"
        return "event", note
    if kind in ("window", "window_all", "evicting_window"):
        assigner = getattr(node.window_transform, "assigner", None)
        if isinstance(assigner, GlobalWindows):
            return "none", ("global windows: fired rows carry the "
                            "eternal window end, not event time")
        if not bool(getattr(assigner, "is_event_time", True)):
            return "processing", ("rows stamped off the operator clock, "
                                  "not the source watermark")
        return "event", "fired at the event watermark"
    if kind == "count_window":
        return "none", ("count windows are event-time-blind: fired rows "
                        "carry the eternal window end")
    if kind in ("session", "cep", "join"):
        return "event", "fired at the event watermark"
    if kind == "global_agg":
        return "event", "upsert rows stamped at the emission watermark"
    # chains/partitions/unions/sinks/async_io/broadcast: pass-through
    if not in_wm:
        return "event", ""
    if all(w == "event" for w in in_wm):
        return "event", ""
    off = next(w for w in in_wm if w != "event")
    return off, "inherited from a non-event-time input leg"


# -- the walk ---------------------------------------------------------------

def _propagate(plan, config) -> PlanFacts:
    from flink_tpu.api.sources import source_is_bounded

    try:
        from flink_tpu.log.connectors import LogSource
    except Exception:  # pragma: no cover - log plane not importable
        LogSource = ()  # type: ignore[assignment]

    upstream: Dict[int, List[int]] = {nid: [] for nid in plan.nodes}
    for n in plan.nodes.values():
        for d in n.downstream:
            upstream[d].append(n.id)

    out: Dict[str, List[Finding]] = {r: [] for r in _WALK_RULES}
    facts: Dict[int, NodeFacts] = {}

    for nid in plan.topo_order:
        node = plan.nodes[nid]
        ups = [facts[u] for u in upstream[nid]]
        nf = NodeFacts(node_id=nid, kind=node.kind, name=node.name)
        nf.log_tainted = any(u.log_tainted for u in ups)
        nf.bounded_input = all(u.bounded_input for u in ups)
        nf.in_schema = ups[0].schema if len(ups) == 1 else None
        nf.wm, nf.wm_note = _wm_facts(node, [u.wm for u in ups])
        nf.state, nf.state_detail, nf.state_bytes_per_key = \
            _state_facts(node, config)

        if node.kind == "source":
            nf.schema = _source_schema(node.source)
            nf.schema_note = ("declared" if nf.schema is not None
                              else "no declared schema")
            nf.log_tainted = isinstance(node.source, LogSource)
            try:
                nf.bounded_input = source_is_bounded(node.source)
            except Exception:
                nf.bounded_input = True
        elif node.kind == "chain":
            if nf.in_schema is None:
                nf.schema = None
                nf.schema_note = ups[0].schema_note if ups else ""
            elif not _eval_chains_enabled():
                nf.schema = None
                nf.schema_note = ("user fns not evaluated at submit — "
                                  "run `flink_tpu analyze` for full "
                                  "schema facts")
            else:
                nf.schema = _eval_chain(nf, node.fns, nf.in_schema, out)
                if nf.schema is not None:
                    nf.schema_note = "inferred (abstract eval)"
        elif node.kind == "union":
            known = [u for u in ups if u.schema is not None]
            if len(known) == len(ups) and ups:
                sets = [frozenset(u.schema) for u in known]
                if len(set(sets)) > 1:
                    legs = "; ".join(
                        f"node {u.node_id} ({u.name!r}): "
                        f"{sorted(u.schema)}" for u in known)
                    out["SCHEMA_MISMATCH_UNION"].append(_f(
                        f"union {node.name!r} merges streams with "
                        f"different field sets — {legs} — downstream "
                        "field references crash on one leg's batches",
                        fix="project both legs to one schema (map) "
                            "before the union",
                        node=nid, node_name=node.name))
                    nf.schema = None
                    nf.schema_note = "leg schemas disagree"
                else:
                    nf.schema = dict(known[0].schema)
                    dt = [u for u in known
                          if u.schema != known[0].schema]
                    nf.schema_note = ("merged"
                                      if not dt else
                                      "merged (leg dtypes differ)")
            else:
                nf.schema = None
                nf.schema_note = "a leg's schema is unknown"
        elif node.kind == "join":
            wt = node.window_transform
            lf = facts.get(node.left_input)
            rf = facts.get(node.right_input)
            if lf is not None:
                _check_fields(nf, lf.schema,
                              (wt.left_key,) + tuple(wt.left_fields),
                              "reads left-side field", out)
            if rf is not None:
                _check_fields(nf, rf.schema,
                              (wt.right_key,) + tuple(wt.right_fields),
                              "reads right-side field", out)
            nf.schema = node.out_schema
            nf.schema_note = "declared by the lowering" if nf.schema else ""
        elif node.kind in ("window", "evicting_window", "count_window",
                           "session", "process", "cep", "global_agg"):
            # the keyBy exchange folds into the op; whether the key
            # column exists is a schema fact either way
            _check_fields(nf, nf.in_schema, [node.key_field],
                          "keys by field", out)
            agg = getattr(node.window_transform, "aggregate", None)
            agg_fields = getattr(agg, "fields", None)
            if agg_fields:
                _check_fields(nf, nf.in_schema, agg_fields,
                              "aggregates over field", out)
            nf.schema = node.out_schema
            nf.schema_note = "declared by the lowering" if nf.schema else ""
        elif node.kind == "window_all":
            agg = getattr(node.window_transform, "aggregate", None)
            agg_fields = getattr(agg, "fields", None)
            if agg_fields:
                _check_fields(nf, nf.in_schema, agg_fields,
                              "aggregates over field", out)
            nf.schema = node.out_schema
            nf.schema_note = "declared by the lowering" if nf.schema else ""
        elif node.kind in ("async_io", "broadcast_connect"):
            nf.schema = None
            nf.schema_note = "user fn output not modeled"
        else:  # partition, sink: pass-through
            nf.schema = ups[0].schema if ups else None
            nf.schema_note = ups[0].schema_note if ups else ""

        # changelog axis: retract-mode ops MINT op-typed output;
        # pass-through nodes carry it; every other stateful operator
        # emits fresh fired rows (the axis resets there — a window agg
        # over changelog input FOLDS the retractions, it does not
        # forward them)
        wt = getattr(node, "window_transform", None)
        if (node.kind in ("global_agg", "session")
                and getattr(wt, "retract", False)):
            nf.changelog = True
        elif node.kind in ("chain", "partition", "union", "sink"):
            nf.changelog = any(u.changelog for u in ups)
            if (nf.changelog and node.kind == "chain"
                    and nf.schema is not None and "__op__" not in nf.schema):
                nf.changelog = False  # a map projected the op column away
        facts[nid] = nf

    return PlanFacts(nodes=facts, upstream=upstream, findings=out)


# -- the dataflow rule catalog ----------------------------------------------

@plan_rule("FIELD_NOT_IN_SCHEMA", "error", plane="dataflow",
           fix="carry the field through upstream maps, or fix the name")
def field_not_in_schema(plan, config) -> Iterable[Finding]:
    """A keyBy / aggregate / join / chain references a field that no
    longer exists in its input schema (dropped or renamed upstream) —
    a guaranteed KeyError or wrong-column partitioning at runtime,
    caught by propagating source-declared schemas through the plan."""
    return propagate(plan, config).findings["FIELD_NOT_IN_SCHEMA"]


@plan_rule("SCHEMA_MISMATCH_UNION", "error", plane="dataflow",
           fix="project both legs to one schema before the union")
def schema_mismatch_union(plan, config) -> Iterable[Finding]:
    """A union merges streams whose field sets disagree: batches flow
    through alternately, so every downstream field reference crashes on
    one leg's batches (or silently reads a column that is sometimes
    absent)."""
    return propagate(plan, config).findings["SCHEMA_MISMATCH_UNION"]


@plan_rule("UNBOUNDED_STATE_GROWTH", "error", plane="dataflow",
           fix="use a purging trigger / evictor, or bound the window")
def unbounded_state_growth(plan, config) -> Iterable[Finding]:
    """A stateful operator whose state can only grow — a GlobalWindows
    buffer with a non-purging trigger, a count window that never purges
    — fed by an UNBOUNDED source in streaming mode: the job leaks until
    the state backend fails. (Bounded inputs cap state at end-of-input
    and stay silent; batch mode is re-execution and is skipped.)"""
    from flink_tpu.config import ExecutionOptions

    mode = str(config.get(ExecutionOptions.RUNTIME_MODE)).strip().lower()
    if mode == "batch":
        return
    for nf in propagate(plan, config).nodes.values():
        if nf.state == "unbounded" and not nf.bounded_input:
            yield _f(
                f"{nf.kind} {nf.name!r} has unbounded state growth "
                f"({nf.state_detail}) and is fed by an unbounded "
                "source — state grows until the backend fails",
                fix="purge at fire (PurgingTrigger / count_window), "
                    "set an evictor, or use a time-bounded assigner",
                node=nf.node_id, node_name=nf.name)


@plan_rule("STALLED_WATERMARK_LEG", "error", plane="dataflow",
           fix="feed event-time operators from event-time legs only")
def stalled_watermark_leg(plan, config) -> Iterable[Finding]:
    """An event-time operator fed by a leg whose rows carry no event
    time (processing-time windows, count/global windows): the pipeline
    watermark advances from SOURCE event timestamps, so the panes this
    leg's rows land in are never meaningfully crossed — the operator
    sits on its state forever (or fires garbage windows)."""
    from flink_tpu.analysis.plan_rules import (
        _EVENT_TIME_KINDS, _is_event_time)

    facts = propagate(plan, config)
    for nf in facts.nodes.values():
        node = plan.nodes[nf.node_id]
        if node.kind not in _EVENT_TIME_KINDS or not _is_event_time(node):
            continue
        for u in facts.upstream[nf.node_id]:
            uf = facts.nodes[u]
            if uf.wm != "event":
                axis = ("no time axis" if uf.wm == "none"
                        else "the processing-time axis")
                yield _f(
                    f"event-time {nf.kind} {nf.name!r} is fed by node "
                    f"{u} ({uf.name!r}), whose rows carry {axis} "
                    f"({uf.wm_note}) — the source-driven event "
                    "watermark can never meaningfully cross this leg's "
                    "windows",
                    fix="keep the leg on event time, or switch this "
                        "operator to a processing-time assigner",
                    node=nf.node_id, node_name=nf.name)


@plan_rule("NON_TXN_SINK_IN_CHAIN", "error", plane="dataflow",
           fix="use a TwoPhaseCommitSink on log-chained paths")
def non_txn_sink_in_chain(plan, config) -> Iterable[Finding]:
    """A job reading a durable-log topic (LogSource — the exactly-once
    job-chaining plane, PR 3) writes through a NON-transactional sink
    while checkpointing: a recovery replays the un-checkpointed tail
    into the sink, silently breaking the end-to-end exactly-once chain
    the upstream job's 2PC commit paid for. Escalates the generic
    NON_TRANSACTIONAL_SINK warning to an error on tainted paths."""
    from flink_tpu.api.sinks import sink_is_transactional
    from flink_tpu.config import CheckpointingOptions

    if config.get(CheckpointingOptions.INTERVAL) <= 0:
        return
    facts = propagate(plan, config)
    for nf in facts.nodes.values():
        node = plan.nodes[nf.node_id]
        if node.kind != "sink" or node.sink is None or not nf.log_tainted:
            continue
        if not sink_is_transactional(node.sink):
            yield _f(
                f"sink {nf.name!r} ({type(node.sink).__name__}) is "
                "downstream of a "
                "LogSource but not transactional — recovery replays the "
                "un-checkpointed tail into it, breaking the end-to-end "
                "exactly-once chain the upstream job's 2PC commit "
                "established",
                fix="use a TwoPhaseCommitSink (LogSink, FileSink, "
                    "TransactionalCollectSink) on log-chained paths",
                node=nf.node_id, node_name=nf.name)


@plan_rule("CHANGELOG_SINK_MISMATCH", "error", plane="dataflow",
           fix="use a changelog-capable sink (RetractSink / UpsertSink)")
def changelog_sink_mismatch(plan, config) -> Iterable[Finding]:
    """A retract-producing operator (retract-mode GROUP BY / session
    aggregation) feeds an append-only sink: the sink appends -U/+U
    pairs as if they were independent inserts, so every key update
    lands TWICE and the materialized result silently double-counts —
    the op-typed rows only mean something to a sink that folds them
    (``Sink.changelog_capable``)."""
    facts = propagate(plan, config)
    for nf in facts.nodes.values():
        node = plan.nodes[nf.node_id]
        if node.kind != "sink" or node.sink is None or not nf.changelog:
            continue
        if not getattr(node.sink, "changelog_capable", False):
            yield _f(
                f"sink {nf.name!r} ({type(node.sink).__name__}) receives "
                "an op-typed changelog stream (a retract-mode aggregation "
                "is upstream) but is append-only — every -U/+U update "
                "pair is appended as two inserts, silently "
                "double-counting each key update",
                fix="materialize through a changelog-capable sink "
                    "(RetractSink, UpsertSink) or drop retract mode if "
                    "append semantics are intended",
                node=nf.node_id, node_name=nf.name)


@plan_rule("STATE_BYTES_EXCEEDED", "warn", plane="dataflow",
           fix="shrink the window/lateness geometry or raise the budget")
def state_bytes_exceeded(plan, config) -> Iterable[Finding]:
    """A stateful operator's statically-estimated per-key state
    footprint (lane accumulators × live panes from the window/lateness
    geometry — the number `analyze --explain` prints) exceeds the
    configured ``analysis.max-state-bytes-per-key`` budget — the
    admission-control check for jobs sharing a chip's HBM. Off by
    default (budget 0)."""
    from flink_tpu.config import AnalysisOptions

    try:
        budget = int(config.get(AnalysisOptions.MAX_STATE_BYTES_PER_KEY))
    except (TypeError, ValueError):
        budget = 0
    if budget <= 0:
        return
    for nf in propagate(plan, config).nodes.values():
        est = nf.state_bytes_per_key
        if est is not None and est > budget:
            yield _f(
                f"{nf.kind} {nf.name!r} holds an estimated {est} B of "
                f"state per key ({nf.state_detail}), over the "
                f"analysis.max-state-bytes-per-key budget of {budget} B",
                fix="shrink window size / lateness / lane count, or "
                    "raise the budget",
                node=nf.node_id, node_name=nf.name)


# -- explain ----------------------------------------------------------------

def _fmt_schema(schema: Schema, note: str) -> str:
    if schema is None:
        return f"unknown ({note})" if note else "unknown"
    body = ", ".join(f"{k}:{schema[k]}" for k in sorted(schema))
    return "{" + body + "}" + (f" ({note})" if note else "")


def explain_plan(plan, config) -> str:
    """Per-node inferred facts of the propagated lattices — the
    `analyze --explain` surface. One block per node in topological
    order: output schema, watermark axis, state bound (+ bytes-per-key
    estimate where the layout is dense)."""
    facts = propagate(plan, config)
    lines = ["per-node dataflow facts (schema | watermark | state):"]
    for nid in plan.topo_order:
        nf = facts.nodes[nid]
        state = nf.state
        if nf.state_detail:
            state += f" [{nf.state_detail}]"
        if nf.state_bytes_per_key is not None:
            state += f" ~{nf.state_bytes_per_key} B/key"
        wm = nf.wm + (f" ({nf.wm_note})" if nf.wm_note else "")
        if nf.changelog:
            wm += " | changelog (op-typed rows)"
        lines.append(f"node {nid} {nf.kind} {nf.name!r}:")
        lines.append(f"  schema    {_fmt_schema(nf.schema, nf.schema_note)}")
        lines.append(f"  watermark {wm}")
        lines.append(f"  state     {state}")
    return "\n".join(lines)
