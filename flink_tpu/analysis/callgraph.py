"""Project call graph — the interprocedural substrate of the lint plane.

``build_graph({relpath: ast.Module})`` indexes every module of the
linted set into one :class:`CallGraph`: functions (module-level and
nested), classes with their methods and ``self``-attribute bindings,
import aliases, and module-level string/lock constants. ``resolve()``
then maps a call expression to the function definitions it can reach —
the name-resolution forms the interprocedural rules key on:

- **bare names** — ``helper(x)`` to a def in the same module (any
  nesting depth; shadowing is ignored, a documented approximation), or
  through ``from mod import helper``;
- **methods via self-type** — ``self._bump(c)`` to the enclosing
  class's method (base classes resolved when they name a project
  class), and ``self.lease.verify()`` through the recorded binding
  ``self.lease = LeaseManager(...)``;
- **module-qualified calls** — ``bus.commit(...)`` through ``import
  flink_tpu.log.bus as bus`` / ``from flink_tpu.log import bus``, and
  ``ClassName.method(...)`` staticmethod-style calls.

Binding-type tracking rides on the same index: ``x =
threading.Lock()`` / ``self._mu = threading.RLock()`` register *lock
bindings* (module names / class attrs), which the concurrency and
lock-order rules use instead of the retired name-substring-only
heuristic; ``NAME = "literal"`` module constants feed fault-point
liveness resolution.

Honest scope (syntactic, flow-insensitive): no inheritance across
unresolvable bases, no tracking of functions passed as values (other
than the hostpool rule's own closure binding walk), no conditional
rebinding — the LAST textual ``self.attr = Cls(...)`` wins. That is
the precision the protocol lints need; it is not a type checker.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

# constructor names that bind a mutual-exclusion guard — binding-type
# lock recognition (threading.Lock/RLock assignment tracking)
LOCK_CONSTRUCTORS = frozenset(
    ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"))


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition in the linted set."""

    node: ast.AST                   # FunctionDef / AsyncFunctionDef
    module: str                     # dotted module name
    file: str                       # relpath the findings cite
    name: str
    cls: Optional[str] = None       # enclosing class, if any
    # True only for a DIRECT class-body method (reached via self./Class.
    # paths, never by bare name); nested defs inside a method keep the
    # cls tag but stay bare-name-resolvable closures
    is_method: bool = False

    @property
    def qname(self) -> str:
        base = f"{self.cls}.{self.name}" if self.cls else self.name
        return f"{self.module}:{base}"

    def params(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    def body(self) -> Sequence[ast.stmt]:
        return self.node.body


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: str
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    # self.<attr> = SomeClass(...) — attr -> (module_hint, class_name);
    # module_hint "" means "resolve in the binding module's namespace"
    attr_types: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    # self.<attr> = threading.Lock()/RLock()/... (binding-type locks)
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    # some method calls self.<attr>.verify(...) — the syntactic
    # signature of holding an epoch-fenced lease (fencing lint keys
    # on this; detected during indexing so no rule re-walks the class)
    leased: bool = False


@dataclasses.dataclass
class ModuleInfo:
    name: str                       # dotted ("flink_tpu.log.topic")
    file: str                       # relpath
    tree: ast.Module
    # every def keyed by bare name, any nesting depth (the bare-name
    # fallback the hostpool closure walk has always used)
    functions: Dict[str, List[FuncInfo]] = dataclasses.field(
        default_factory=dict)
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    # `import a.b.c as x` / `import a.b.c` -> {"x"/"a": "a.b.c"/"a"}
    import_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    # `from m import n as x` -> {"x": ("m", "n")}
    from_imports: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    # module-level NAME = "literal"
    str_constants: Dict[str, str] = dataclasses.field(default_factory=dict)
    # module-level NAME = threading.Lock()/...
    lock_names: Set[str] = dataclasses.field(default_factory=set)
    # ast.walk(tree) flattened ONCE at index time — every full-tree
    # rule scan iterates this list instead of re-walking (the lint
    # pass runs ~10 rules per module; re-walking dominated its cost)
    nodes: List[ast.AST] = dataclasses.field(default_factory=list)
    # type-bucketed views of `nodes` (same order): most rules only
    # inspect call sites / with statements, a small fraction of nodes
    calls: List[ast.Call] = dataclasses.field(default_factory=list)
    withs: List[ast.AST] = dataclasses.field(default_factory=list)


def _call_ctor_name(value: ast.AST) -> str:
    """The trailing constructor name of ``x = Name(...)`` /
    ``x = mod.Name(...)`` bindings, else ''."""
    if not isinstance(value, ast.Call):
        return ""
    fn = value.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def module_name_for(relpath: str) -> str:
    """Dotted module name for a repo-relative path (``a/b.py`` ->
    ``a.b``; ``a/__init__.py`` -> ``a``)."""
    mod = relpath.replace("\\", "/")
    if mod.endswith(".py"):
        mod = mod[:-3]
    mod = mod.strip("/").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _index_module(name: str, file: str, tree: ast.Module) -> ModuleInfo:
    mi = ModuleInfo(name=name, file=file, tree=tree,
                    nodes=list(ast.walk(tree)))

    def add_func(node: ast.AST, cls: Optional[str],
                 is_method: bool = False) -> FuncInfo:
        fi = FuncInfo(node=node, module=name, file=file,
                      name=node.name, cls=cls, is_method=is_method)
        mi.functions.setdefault(node.name, []).append(fi)
        return fi

    class_nodes = set()

    def walk_class(cnode: ast.ClassDef) -> None:
        ci = ClassInfo(name=cnode.name, module=name)
        for b in cnode.bases:
            if isinstance(b, ast.Name):
                ci.bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                ci.bases.append(b.attr)
        for sub in cnode.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[sub.name] = add_func(sub, cnode.name,
                                                is_method=True)
                class_nodes.add(id(sub))
                # one subtree walk per method: nested defs (kept
                # bare-name-resolvable with the class tag, so closures
                # can resolve self.*), self-attribute bindings, and the
                # self.<attr>.verify(...) lease signature
                for node in ast.walk(sub):
                    if node is not sub and isinstance(
                            node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add_func(node, cnode.name)
                        class_nodes.add(id(node))
                    elif isinstance(node, ast.Assign):
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                ctor = _call_ctor_name(node.value)
                                if ctor in LOCK_CONSTRUCTORS:
                                    ci.lock_attrs.add(t.attr)
                                elif ctor and ctor[:1].isupper():
                                    ci.attr_types[t.attr] = ("", ctor)
                    elif (not ci.leased and isinstance(node, ast.Call)
                          and isinstance(node.func, ast.Attribute)
                          and node.func.attr == "verify"
                          and isinstance(node.func.value, ast.Attribute)
                          and isinstance(node.func.value.value, ast.Name)
                          and node.func.value.value.id == "self"):
                        ci.leased = True
            else:
                # defs hiding under any other class-body statement
                # (nested classes, conditional blocks) are not
                # module-level functions either
                class_nodes.update(
                    id(n) for n in ast.walk(sub)
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)))
        mi.classes[cnode.name] = ci

    # one bucketing pass: imports anywhere (top level or lazy, inside
    # a function) feed alias resolution, calls/withs feed the rules;
    # constants / module-level locks are top level only
    for node in mi.nodes:
        if isinstance(node, ast.Call):
            mi.calls.append(node)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            mi.withs.append(node)
        elif isinstance(node, ast.Import):
            for a in node.names:
                mi.import_aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
                if a.asname:
                    mi.import_aliases[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                mi.from_imports[a.asname or a.name] = (node.module, a.name)
    for node in tree.body:
        if isinstance(node, ast.Assign):
            ctor = _call_ctor_name(node.value)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if (isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    mi.str_constants[t.id] = node.value.value
                elif ctor in LOCK_CONSTRUCTORS:
                    mi.lock_names.add(t.id)

    # defs: top-level, nested, and methods (methods via walk_class so
    # they are tagged with their class)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            walk_class(node)
    for node in mi.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and id(node) not in class_nodes:
            add_func(node, None)
    return mi


class CallGraph:
    """The indexed module set plus call resolution."""

    def __init__(self, modules: Dict[str, ModuleInfo]) -> None:
        self.modules = modules                      # by dotted name
        self.by_file = {m.file: m for m in modules.values()}
        self._by_node: Dict[int, FuncInfo] = {}
        for m in modules.values():
            for fns in m.functions.values():
                for fi in fns:
                    self._by_node[id(fi.node)] = fi

    # -- lookups ----------------------------------------------------------

    def func_of_node(self, node: ast.AST) -> Optional[FuncInfo]:
        return self._by_node.get(id(node))

    def iter_functions(self) -> Iterator[FuncInfo]:
        for m in self.modules.values():
            for fns in m.functions.values():
                yield from fns

    def class_of(self, ctx: Optional[FuncInfo]) -> Optional[ClassInfo]:
        if ctx is None or ctx.cls is None:
            return None
        mi = self.modules.get(ctx.module)
        return mi.classes.get(ctx.cls) if mi else None

    def _resolve_class(self, mi: ModuleInfo,
                       name: str) -> Optional[ClassInfo]:
        if name in mi.classes:
            return mi.classes[name]
        fi = mi.from_imports.get(name)
        if fi and fi[0] in self.modules:
            return self.modules[fi[0]].classes.get(fi[1])
        return None

    def _method(self, ci: Optional[ClassInfo], name: str,
                depth: int = 0) -> List[FuncInfo]:
        """Method lookup with project-resolvable base-class walk."""
        if ci is None or depth > 4:
            return []
        if name in ci.methods:
            return [ci.methods[name]]
        mi = self.modules.get(ci.module)
        if mi is None:
            return []
        for b in ci.bases:
            hit = self._method(self._resolve_class(mi, b), name, depth + 1)
            if hit:
                return hit
        return []

    # -- call resolution --------------------------------------------------

    def _mi(self, ctx: Optional[FuncInfo],
            module: Optional[ModuleInfo] = None) -> Optional[ModuleInfo]:
        if ctx is not None:
            return self.modules.get(ctx.module)
        if module is not None:
            return module
        if len(self.modules) == 1:
            return next(iter(self.modules.values()))
        return None

    def resolve(self, call: ast.Call, ctx: Optional[FuncInfo],
                module: Optional[ModuleInfo] = None) -> List[FuncInfo]:
        """Function definitions this call expression can reach (empty
        when the callee is external / dynamic)."""
        return self.resolve_name(call.func, ctx, module)

    def resolve_name(self, fn: ast.AST, ctx: Optional[FuncInfo],
                     module: Optional[ModuleInfo] = None) -> List[FuncInfo]:
        mi = self._mi(ctx, module)
        if isinstance(fn, ast.Name):
            return self._resolve_bare(mi, fn.id)
        if isinstance(fn, ast.Attribute):
            base = fn.value
            # self.method(...)
            if isinstance(base, ast.Name) and base.id == "self":
                return self._method(self.class_of(ctx), fn.attr)
            # self.attr.method(...) via the recorded self-type binding
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                ci = self.class_of(ctx)
                if ci and base.attr in ci.attr_types:
                    _, cls_name = ci.attr_types[base.attr]
                    owner = self._resolve_class(
                        self.modules.get(ci.module), cls_name) \
                        if ci.module in self.modules else None
                    return self._method(owner, fn.attr)
                return []
            if isinstance(base, ast.Name) and mi is not None:
                # ClassName.method(...) (staticmethod-style)
                ci = self._resolve_class(mi, base.id)
                if ci is not None:
                    return self._method(ci, fn.attr)
                # module-alias call: bus.commit(...) / np.asarray(...)
                target = mi.import_aliases.get(base.id)
                if target is None and base.id in mi.from_imports:
                    fmod, orig = mi.from_imports[base.id]
                    target = f"{fmod}.{orig}"
                if target and target in self.modules:
                    tm = self.modules[target]
                    return [f for f in tm.functions.get(fn.attr, ())
                            if not f.is_method]
        return []

    def _resolve_bare(self, mi: Optional[ModuleInfo],
                      name: str) -> List[FuncInfo]:
        if mi is None:
            return []
        if name in mi.functions:
            return [f for f in mi.functions[name] if not f.is_method]
        fi = mi.from_imports.get(name)
        if fi and fi[0] in self.modules:
            return [f for f in self.modules[fi[0]].functions.get(fi[1], ())
                    if not f.is_method]
        return []

    # -- binding-type lock recognition ------------------------------------

    def is_lock_expr(self, expr: ast.AST, ctx: Optional[FuncInfo],
                     local_locks: Optional[Set[str]] = None,
                     module: Optional[ModuleInfo] = None) -> bool:
        """Is this with-item context expression a tracked lock binding
        (module-level name, ``self.<attr>`` bound to a Lock/RLock/...,
        or a function-local binding recorded in ``local_locks``)?"""
        if isinstance(expr, ast.Name):
            if local_locks and expr.id in local_locks:
                return True
            mi = self._mi(ctx, module)
            return bool(mi and expr.id in mi.lock_names)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            ci = self.class_of(ctx)
            return bool(ci and expr.attr in ci.lock_attrs)
        return False

    def lock_id(self, expr: ast.AST, ctx: Optional[FuncInfo],
                module: Optional[ModuleInfo] = None) -> Optional[str]:
        """Stable identity for a tracked lock expression (the node of
        the lock-order graph), or None when the expression is not an
        unambiguous tracked binding."""
        if isinstance(expr, ast.Name):
            mi = self._mi(ctx, module)
            if mi and expr.id in mi.lock_names:
                return f"{mi.name}:{expr.id}"
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            ci = self.class_of(ctx)
            if ci and expr.attr in ci.lock_attrs:
                return f"{ci.module}:{ci.name}.{expr.attr}"
        return None


def build_graph(trees: Dict[str, ast.Module]) -> CallGraph:
    """Index ``{relpath: parsed module}`` into one CallGraph."""
    modules: Dict[str, ModuleInfo] = {}
    for relpath, tree in trees.items():
        name = module_name_for(relpath)
        modules[name] = _index_module(name, relpath, tree)
    return CallGraph(modules)
