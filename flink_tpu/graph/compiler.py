"""Graph lowering: Transformation DAG → executable stage plan.

ref: the two-step lowering StreamGraphGenerator (streaming/api/graph/
StreamGraphGenerator.java) → StreamingJobGraphGenerator.createJobGraph
(chaining decided in ``isChainable``). Here the chaining analogue fuses
every run of stateless transformations between stateful/exchange
boundaries into ONE host ingest function per stage — and the heavy
lifting (keyed window state, shuffles, aggregation) is inside the
stateful ops' compiled device programs.

The plan is a DAG of ExecNodes the driver walks per microbatch:
  ExecSource → ExecChain (fused stateless fns) → ExecWindowAgg /
  ExecSessionAgg / ExecJoin → ExecChain → ExecSink
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.config import Configuration, PipelineOptions, StateOptions
from flink_tpu.graph.transformations import (
    EvictingWindowTransformation,
    BroadcastConnectTransformation,
    KeyByTransformation,
    MapTransformation,
    AsyncIOTransformation,
    CepTransformation,
    CountWindowAggregateTransformation,
    GlobalAggregateTransformation,
    KeyedProcessTransformation,
    PartitionTransformation,
    SessionAggregateTransformation,
    WindowAllAggregateTransformation,
    SinkTransformation,
    SourceTransformation,
    Transformation,
    UnionTransformation,
    WindowAggregateTransformation,
    WindowJoinTransformation,
)
from flink_tpu.time.watermarks import WatermarkStrategy


@dataclasses.dataclass
class ExecNode:
    id: int
    kind: str                 # source | chain | window | session | join | sink | union
    downstream: List[int] = dataclasses.field(default_factory=list)
    # kind-specific payloads
    source: Any = None
    watermark_strategy: Optional[WatermarkStrategy] = None
    fns: List[Callable] = dataclasses.field(default_factory=list)
    key_field: str = "key"
    key_fn: Optional[Callable] = None
    window_transform: Any = None
    sink: Any = None
    # join: which input edge is left/right (by upstream node id)
    left_input: Optional[int] = None
    right_input: Optional[int] = None
    # partition: non-keyed redistribution strategy (exchange boundary)
    partition_strategy: Optional[str] = None
    # keyed stateful ops: whether the op's input edge came through a
    # keyBy exchange (the lowering folds KeyByTransformation into the
    # op, so the plan must remember the exchange existed — the
    # analyzer's KEYED_WITHOUT_KEYBY rule reads this)
    keyed_input: bool = False
    # declared OUTPUT record schema (field → numpy dtype name) of this
    # node's emitted rows, recorded at lowering for the operator kinds
    # whose fired-row shape is a plan fact (key/window columns + the
    # aggregate's probed result fields). None = not statically known
    # (chains, opaque window fns, CEP matches). The analyzer's dataflow
    # plane reads this the way KEYED_OP_WITHOUT_KEYBY reads
    # ``keyed_input`` (analysis/dataflow.py).
    out_schema: Optional[Dict[str, str]] = None
    name: str = ""


@dataclasses.dataclass
class ExecutionPlan:
    nodes: Dict[int, ExecNode]
    sources: List[int]
    topo_order: List[int]
    watermark_strategy: WatermarkStrategy
    # bounded-execution plan (execution.runtime-mode=batch, SURVEY
    # §3.7): stage_of levels every node into a topological wave;
    # blocking_edges are the (upstream, stateful-consumer) edges the
    # driver materializes through the blocking shuffle instead of
    # pushing through. Empty/default in streaming mode.
    runtime_mode: str = "streaming"
    stage_of: Dict[int, int] = dataclasses.field(default_factory=dict)
    blocking_edges: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)

    def node(self, nid: int) -> ExecNode:
        return self.nodes[nid]


# Stateful operator kinds whose input edge becomes BLOCKING in batch
# mode — the exchange boundary of the reference's batch shuffles
# (§3.6): the consumer must not see a single record until the producer
# stage ran to completion. Chains/unions/partitions/sinks stay
# pipelined within their stage (the isChainable rule: only exchange
# edges block). async_io blocks too: its in-flight draining is driven
# by the per-step watermark pass that batch mode deliberately skips,
# so the batch driver owns its submit/poll cycle at a stage head.
STAGE_HEAD_KINDS = frozenset((
    "window", "session", "join", "count_window", "window_all",
    "process", "cep", "evicting_window", "global_agg",
    "broadcast_connect", "async_io",
))


def assign_stages(
    nodes: Dict[int, ExecNode], topo: List[int],
) -> Tuple[Dict[int, int], List[Tuple[int, int]]]:
    """Level every node into topological waves: a stateful consumer
    lives one wave below its producers (its input edges block); every
    other node joins its deepest producer's wave (pipelined). The wave
    number IS the scheduling order (runtime/scheduler.py runs waves
    sequentially — the topological-wave analogue of batch pipelined-
    region scheduling over BLOCKING result partitions)."""
    upstream: Dict[int, List[int]] = {nid: [] for nid in nodes}
    for n in nodes.values():
        for d in n.downstream:
            upstream[d].append(n.id)
    stage_of: Dict[int, int] = {}
    blocking: List[Tuple[int, int]] = []
    for nid in topo:
        ups = upstream[nid]
        base = max((stage_of[u] for u in ups), default=0)
        if nodes[nid].kind in STAGE_HEAD_KINDS:
            if len(set(ups)) != len(ups):
                # s.join(s) / s.connect(s): both inputs are the SAME
                # producer node, so the two logical edges collapse onto
                # one (u, v) key — the partition-file exchange cannot
                # tell the sides apart. Reject rather than corrupt.
                raise NotImplementedError(
                    f"batch mode does not support a two-input operator "
                    f"({nodes[nid].kind} {nodes[nid].name!r}) fed twice "
                    "by the same upstream node (self-join/self-connect)"
                    " — materialize one side through a distinct map "
                    "first")
            stage_of[nid] = base + 1
            blocking.extend((u, nid) for u in ups)
        else:
            stage_of[nid] = base
    return stage_of, blocking


def _probe_result_schema(agg) -> Dict[str, str]:
    """Result-field names + coarse dtypes of a LaneAggregate, via the
    shared empty-lane probe (ops/aggregates.probe_finalize — the same
    source WindowOperator._result_fields classifies dtypes from):
    integer-classified lanes emit int64 columns, the rest float32."""
    from flink_tpu.ops.aggregates import probe_finalize

    return {
        k: ("int64" if np.issubdtype(np.asarray(v).dtype, np.integer)
            else "float32")
        for k, v in probe_finalize(agg).items()}


def _op_out_schema(node: ExecNode) -> Optional[Dict[str, str]]:
    """The statically-known fired-row schema of a stateful op — the
    (key, window_start, window_end, count) columns every windowed
    operator emits plus the aggregate's probed result fields (kept in
    lockstep with ops/{window,session,count_window,global_agg,
    window_all,join}.py output assembly). None when the output shape is
    not a plan fact (opaque window fns, CEP match rows, async
    enrichment)."""
    wt = node.window_transform
    try:
        if node.kind in ("window", "session", "count_window"):
            out = {"key": "int64", "window_start": "int64",
                   "window_end": "int64", "count": "int64"}
            out.update(_probe_result_schema(wt.aggregate))
            if getattr(wt, "retract", False):
                out["__op__"] = "int8"  # records.OP_FIELD changelog lane
            return out
        if node.kind == "window_all":
            out = {"window_start": "int64", "window_end": "int64",
                   "count": "int64"}
            out.update(_probe_result_schema(wt.aggregate))
            return out
        if node.kind == "global_agg":
            out = {"key": "int64", "count": "int64"}
            out.update(_probe_result_schema(wt.aggregate))
            if getattr(wt, "retract", False):
                out["__op__"] = "int8"  # records.OP_FIELD changelog lane
            return out
        if node.kind == "join":
            out = {"key": "int64", "window_start": "int64",
                   "window_end": "int64"}
            if wt.mode == "aggregate":
                out["left_count"] = "int64"
                out["right_count"] = "int64"
            for f in wt.left_fields:
                out[f"left_{f}"] = "float32"
            for f in wt.right_fields:
                out[f"right_{f}"] = "float32"
            return out
    except Exception:
        # schema recording must never fail a lowering the runtime would
        # accept (a user aggregate whose finalize rejects empty lanes)
        return None
    return None


def compile_job(
    transforms: Sequence[Transformation],
    config: Configuration,
    default_wm: WatermarkStrategy,
    strict: bool = True,
) -> ExecutionPlan:
    """Lower the transformation list. Chaining rule (the isChainable
    analogue): consecutive Map/Filter/FlatMap nodes with a single
    consumer fuse into one ExecChain; KeyBy folds into the downstream
    stateful op (the exchange lives inside its device program).

    ``strict=False`` lowers a plan that strict compilation would
    reject (unbounded sources in batch mode) so the plan ANALYZER can
    report the violation as a structured finding instead of dying on
    the first hard error — the execution path always compiles strict."""
    # consumers per transformation
    consumers: Dict[int, List[Transformation]] = {}
    for t in transforms:
        for up in t.inputs:
            consumers.setdefault(up.id, []).append(t)

    nodes: Dict[int, ExecNode] = {}
    t2node: Dict[int, int] = {}  # transformation id -> exec node id
    next_id = [0]

    def new_node(kind: str, name: str, **kw) -> ExecNode:
        n = ExecNode(id=next_id[0], kind=kind, name=name, **kw)
        next_id[0] += 1
        nodes[n.id] = n
        return n

    def keyed_in(t: Transformation) -> bool:
        """Whether t's input edge is a keyBy exchange (KeyBy folds
        into the downstream stateful op, so the plan records the
        exchange on the op — analysis/plan_rules.py
        KEYED_OP_WITHOUT_KEYBY reads this)."""
        return isinstance(t.inputs[0], KeyByTransformation)

    def node_for(t: Transformation) -> int:
        """Exec node that PRODUCES t's output batches."""
        if t.id in t2node:
            return t2node[t.id]
        if isinstance(t, SourceTransformation):
            n = new_node("source", t.name, source=t.source,
                         watermark_strategy=t.watermark_strategy)
        elif isinstance(t, MapTransformation):
            up = node_for(t.inputs[0])
            upn = nodes[up]
            # chain into upstream chain node if it's a chain with a
            # single consumer path (always true here: we create chains
            # per linear run)
            if upn.kind == "chain" and len(consumers.get(t.inputs[0].id, [])) == 1:
                upn.fns.append(t.fn)
                t2node[t.id] = upn.id
                return upn.id
            n = new_node("chain", t.name, fns=[t.fn])
            upn.downstream.append(n.id)
        elif isinstance(t, KeyByTransformation):
            # keyBy is virtual: the downstream stateful op reads key_field
            up = node_for(t.inputs[0])
            t2node[t.id] = up
            # key_fn materializes the key column via an appended chain fn;
            # fuse into the upstream chain only when this keyBy is its
            # sole consumer (sibling branches must not see the injected
            # key column — same single-consumer rule as map chaining)
            if t.key_fn is not None:
                fn = t.key_fn

                def add_key(data, ts, valid, _fn=fn, _kf=t.key_field):
                    data = dict(data)
                    data[_kf] = np.asarray(_fn(data), np.int64)
                    return data, ts, valid

                upn = nodes[up]
                if (upn.kind == "chain"
                        and len(consumers.get(t.inputs[0].id, [])) == 1):
                    upn.fns.append(add_key)
                else:
                    n = new_node("chain", "key_extract", fns=[add_key])
                    upn.downstream.append(n.id)
                    t2node[t.id] = n.id
                    return n.id
            return up
        elif isinstance(t, WindowAggregateTransformation):
            up = node_for(t.inputs[0])
            n = new_node("window", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, EvictingWindowTransformation):
            up = node_for(t.inputs[0])
            n = new_node("evicting_window", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, AsyncIOTransformation):
            up = node_for(t.inputs[0])
            n = new_node("async_io", t.name, window_transform=t)
            nodes[up].downstream.append(n.id)
        elif isinstance(t, PartitionTransformation):
            # an exchange boundary: always its own node (breaks the
            # chain — the isChainable rule excludes non-forward edges)
            up = node_for(t.inputs[0])
            n = new_node("partition", t.name, partition_strategy=t.strategy)
            nodes[up].downstream.append(n.id)
        elif isinstance(t, CepTransformation):
            up = node_for(t.inputs[0])
            n = new_node("cep", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, KeyedProcessTransformation):
            up = node_for(t.inputs[0])
            n = new_node("process", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, WindowAllAggregateTransformation):
            up = node_for(t.inputs[0])
            n = new_node("window_all", t.name, window_transform=t)
            nodes[up].downstream.append(n.id)
        elif isinstance(t, CountWindowAggregateTransformation):
            up = node_for(t.inputs[0])
            n = new_node("count_window", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, GlobalAggregateTransformation):
            up = node_for(t.inputs[0])
            n = new_node("global_agg", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, SessionAggregateTransformation):
            up = node_for(t.inputs[0])
            n = new_node("session", t.name, window_transform=t,
                         key_field=t.key_field, keyed_input=keyed_in(t))
            nodes[up].downstream.append(n.id)
        elif isinstance(t, WindowJoinTransformation):
            lup = node_for(t.inputs[0])
            rup = node_for(t.inputs[1])
            n = new_node("join", t.name, window_transform=t,
                         left_input=lup, right_input=rup)
            nodes[lup].downstream.append(n.id)
            nodes[rup].downstream.append(n.id)
        elif isinstance(t, BroadcastConnectTransformation):
            # left = data stream, right = control (broadcast) stream
            lup = node_for(t.inputs[0])
            rup = node_for(t.inputs[1])
            n = new_node("broadcast_connect", t.name, window_transform=t,
                         left_input=lup, right_input=rup)
            nodes[lup].downstream.append(n.id)
            nodes[rup].downstream.append(n.id)
        elif isinstance(t, SinkTransformation):
            up = node_for(t.inputs[0])
            n = new_node("sink", t.name, sink=t.sink)
            nodes[up].downstream.append(n.id)
        elif isinstance(t, UnionTransformation):
            n = new_node("union", t.name)
            for inp in t.inputs:
                up = node_for(inp)
                nodes[up].downstream.append(n.id)
        else:
            raise NotImplementedError(f"transformation {type(t).__name__}")
        t2node[t.id] = n.id
        return n.id

    for t in transforms:
        node_for(t)

    sources = [n.id for n in nodes.values() if n.kind == "source"]
    if not sources:
        raise ValueError("job has no sources")
    sinks = [n for n in nodes.values() if n.kind == "sink"]
    if not sinks:
        raise ValueError("job has no sinks (add_sink/print/collect)")

    topo = _topo_order(nodes, sources)

    # record each stateful op's declared output schema (the analyzer's
    # dataflow plane seeds field-reference checks downstream of the op
    # from this, the way keyed_input records the folded keyBy exchange)
    for n in nodes.values():
        n.out_schema = _op_out_schema(n)

    from flink_tpu.config import ExecutionOptions

    mode = str(config.get(ExecutionOptions.RUNTIME_MODE)).strip().lower()
    if mode not in ("streaming", "batch"):
        raise ValueError(
            f"execution.runtime-mode must be 'streaming' or 'batch', "
            f"got {mode!r}")
    stage_of: Dict[int, int] = {}
    blocking: List[Tuple[int, int]] = []
    if mode == "batch":
        from flink_tpu.api.sources import source_is_bounded

        unbounded = [nodes[sid].name or str(sid) for sid in sources
                     if not source_is_bounded(nodes[sid].source)]
        if unbounded and strict:
            raise ValueError(
                "execution.runtime-mode=batch requires every source to "
                f"be bounded; unbounded source(s): {unbounded} (run "
                "them in streaming mode, or bound the generator)")
        stage_of, blocking = assign_stages(nodes, topo)
    return ExecutionPlan(nodes=nodes, sources=sources, topo_order=topo,
                         watermark_strategy=default_wm, runtime_mode=mode,
                         stage_of=stage_of, blocking_edges=blocking)


def _topo_order(nodes: Dict[int, ExecNode], sources: List[int]) -> List[int]:
    indeg: Dict[int, int] = {nid: 0 for nid in nodes}
    for n in nodes.values():
        for d in n.downstream:
            indeg[d] += 1
    order: List[int] = []
    ready = [nid for nid, d in indeg.items() if d == 0]
    while ready:
        nid = ready.pop()
        order.append(nid)
        for d in nodes[nid].downstream:
            indeg[d] -= 1
            if indeg[d] == 0:
                ready.append(d)
    if len(order) != len(nodes):
        raise ValueError("cycle in transformation graph")
    return order
