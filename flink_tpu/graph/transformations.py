"""Logical transformation DAG — what the fluent API builds.

ref: streaming/api/transformations/{OneInputTransformation,
PartitionTransformation,SourceTransformation,SinkTransformation,
UnionTransformation}.java — each fluent call appends one node; nothing
executes until the graph is lowered and run (lazy, like the reference's
StreamExecutionEnvironment.execute()).

TPU-first notes: transformations carry no parallelism (parallelism is a
property of the device mesh chosen at execution, not of graph nodes), and
the stateless ones carry jax-traceable batch functions that the lowering
step fuses into one compiled step function per stage (the operator
chaining analogue; ref: StreamingJobGraphGenerator.isChainable).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.api.windowing import Trigger, WindowAssigner
from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.time.watermarks import WatermarkStrategy

_ids = itertools.count()


@dataclasses.dataclass
class Transformation:
    """Base DAG node. ``inputs`` are upstream transformations."""

    name: str
    inputs: Tuple["Transformation", ...] = ()

    def __post_init__(self) -> None:
        self.id = next(_ids)

    def __hash__(self) -> int:
        return self.id

    def __eq__(self, other: object) -> bool:
        return self is other


@dataclasses.dataclass(eq=False)
class SourceTransformation(Transformation):
    """ref: SourceTransformation.java + the FLIP-27 Source seam
    (flink-core/.../api/connector/source/Source.java)."""

    source: Any = None  # flink_tpu.api.sources.Source
    watermark_strategy: Optional[WatermarkStrategy] = None


@dataclasses.dataclass(eq=False)
class MapTransformation(Transformation):
    """map/filter/flatMap — chainable stateless batch fns
    (ref: OneInputTransformation wrapping StreamMap/StreamFilter/
    StreamFlatMap operators)."""

    # fn(data: dict, ts, valid) -> (data, ts, valid); traced into the
    # stage step function
    fn: Optional[Callable] = None
    kind: str = "map"  # map | filter | flatmap | process


@dataclasses.dataclass(eq=False)
class KeyByTransformation(Transformation):
    """Hash partition by key (ref: PartitionTransformation with
    KeyGroupStreamPartitioner). key_field names an int64 column; key_fn
    optionally derives it on device first."""

    key_field: str = "key"
    key_fn: Optional[Callable] = None


@dataclasses.dataclass(eq=False)
class WindowAggregateTransformation(Transformation):
    """Keyed window + aggregate (ref: WindowedStream.aggregate →
    WindowOperator via WindowOperatorBuilder)."""

    assigner: Optional[WindowAssigner] = None
    aggregate: Optional[LaneAggregate] = None
    trigger: Optional[Trigger] = None
    allowed_lateness_ms: int = 0
    key_field: str = "key"
    # (result_field, n): fuse a per-window top-n (ties kept) into the
    # window operator's device fire path (set via DataStream.top)
    top_n: Optional[Tuple[str, int]] = None


@dataclasses.dataclass(eq=False)
class EvictingWindowTransformation(Transformation):
    """Keyed window with an evictor and/or a custom user trigger — the
    element-buffer path (ref: WindowedStream.evictor/trigger →
    EvictingWindowOperator; see ops/evicting_window.py for why this
    cannot ride the pane kernels)."""

    assigner: Optional[WindowAssigner] = None
    window_fn: Any = None        # fn(elements dict incl __ts__) -> row dict
    trigger: Optional[Trigger] = None
    evictor: Any = None
    allowed_lateness_ms: int = 0
    key_field: str = "key"


@dataclasses.dataclass(eq=False)
class AsyncIOTransformation(Transformation):
    """Async external enrichment (ref: AsyncDataStream.orderedWait /
    unorderedWait -> AsyncWaitOperator; see ops/async_io.py)."""

    fn: Any = None                # AsyncFunction or callable(data, ts)
    capacity: int = 8
    timeout_ms: int = 60_000
    ordered: bool = True


@dataclasses.dataclass(eq=False)
class PartitionTransformation(Transformation):
    """Non-keyed redistribution (ref: PartitionTransformation.java with
    the streaming/runtime/partitioner family). ``strategy`` is one of
    rebalance|rescale|shuffle|broadcast|global|forward — lowered to an
    exchange boundary that breaks operator chaining; the subtask
    assignment itself lives in exchange/partitioners.py."""

    strategy: str = "rebalance"


@dataclasses.dataclass(eq=False)
class CepTransformation(Transformation):
    """Keyed pattern matching (ref: cep/PatternStream → CepOperator;
    see flink_tpu/cep.py)."""

    pattern: Any = None
    key_field: str = "key"


@dataclasses.dataclass(eq=False)
class KeyedProcessTransformation(Transformation):
    """Keyed process function with state + timers (ref: KeyedStream
    .process → KeyedProcessOperator; see ops/process.py)."""

    fn: Any = None  # api.functions.KeyedProcessFunction
    key_field: str = "key"


@dataclasses.dataclass(eq=False)
class WindowAllAggregateTransformation(Transformation):
    """Non-keyed global window + aggregate (ref: DataStream.windowAll →
    AllWindowedStream at parallelism 1; here a host-side pane reduce
    with NO single-shard funnel — see ops/window_all.py)."""

    assigner: Optional[WindowAssigner] = None
    aggregate: Optional[LaneAggregate] = None
    allowed_lateness_ms: int = 0


@dataclasses.dataclass(eq=False)
class CountWindowAggregateTransformation(Transformation):
    """Keyed count window (ref: KeyedStream.countWindow = GlobalWindows
    + PurgingTrigger(CountTrigger(n)); lowered to a vectorized per-step
    trigger mask — see ops/count_window.py)."""

    size: int = 0
    purge: bool = True
    aggregate: Optional[LaneAggregate] = None
    key_field: str = "key"


@dataclasses.dataclass(eq=False)
class GlobalAggregateTransformation(Transformation):
    """Unwindowed keyed running aggregation emitting an upsert stream
    (ref: table-runtime GroupAggFunction / retract-changelog semantics
    degenerated to upserts for insert-only input — see
    ops/global_agg.py). ``retract=True`` emits the full op-typed
    changelog instead (-U/+U pairs, records.OP_FIELD lane)."""

    aggregate: Optional[LaneAggregate] = None
    key_field: str = "key"
    retract: bool = False


@dataclasses.dataclass(eq=False)
class WindowJoinTransformation(Transformation):
    """Two-input tumbling-window equi-join (ref: streaming/api/datastream/
    JoinedStreams.java lowered onto WindowOperator with a union state;
    here a dedicated two-family pane join — Q8)."""

    assigner: Optional[WindowAssigner] = None
    left_key: str = "key"
    right_key: str = "key"
    left_fields: Tuple[str, ...] = ()
    right_fields: Tuple[str, ...] = ()
    mode: str = "pairs"  # "pairs" (exact) | "aggregate" (cogroup summary)


@dataclasses.dataclass(eq=False)
class SessionAggregateTransformation(Transformation):
    """Keyed session windows (ref: EventTimeSessionWindows +
    MergingWindowSet) — host span registry + device accumulators.
    ``retract=True`` op-types the output: a merge that consumes an
    already-fired span retracts its stale row (-U) before the merged
    session (re)fires (+U)."""

    gap_ms: int = 0
    aggregate: Optional[LaneAggregate] = None
    allowed_lateness_ms: int = 0
    key_field: str = "key"
    retract: bool = False


@dataclasses.dataclass(eq=False)
class SinkTransformation(Transformation):
    """ref: SinkTransformation.java + Sink API v2
    (flink-core/.../api/connector/sink2/Sink.java)."""

    sink: Any = None  # flink_tpu.api.sinks.Sink


@dataclasses.dataclass(eq=False)
class UnionTransformation(Transformation):
    """ref: UnionTransformation.java — merge same-schema streams."""


@dataclasses.dataclass(eq=False)
class BroadcastConnectTransformation(Transformation):
    """Two-input broadcast connect: inputs = (data stream, control
    stream); the control side replicates into broadcast state (ref:
    BroadcastConnectedStream + CoBroadcastWithNonKeyedOperator)."""

    fn: Any = None
