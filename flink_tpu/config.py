"""Typed configuration system.

Reproduces the capability of the reference's Configuration stack
(ref: flink-core/.../configuration/Configuration.java, ConfigOption.java,
ConfigOptions.java, GlobalConfiguration.java): typed options with defaults
and doc strings, addressable as dotted ``a.b.c`` keys, layered resolution
(defaults < file < env < explicit overrides).

TPU-first deltas: no YAML dependency required (plain ``key: value`` /
JSON files both parse); options that shape compiled programs (microbatch
size, key shards, pane ring length) are surfaced here because they become
*static* shapes under jit.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Generic, Iterator, Mapping, Optional, TypeVar

T = TypeVar("T")

_REGISTRY: Dict[str, "ConfigOption[Any]"] = {}


@dataclasses.dataclass(frozen=True)
class ConfigOption(Generic[T]):
    """A typed option constant (ref: ConfigOption.java).

    ``parse`` converts a string (env/file) representation to ``T``.
    """

    key: str
    default: T
    description: str = ""
    parse: Optional[Callable[[str], T]] = None

    def __post_init__(self) -> None:
        _REGISTRY[self.key] = self

    def _coerce(self, raw: Any) -> T:
        if isinstance(raw, str) and self.parse is not None:
            return self.parse(raw)
        if isinstance(raw, str) and isinstance(self.default, bool):
            return raw.strip().lower() in ("1", "true", "yes", "on")  # type: ignore[return-value]
        if isinstance(raw, str) and isinstance(self.default, int):
            return int(raw)  # type: ignore[return-value]
        if isinstance(raw, str) and isinstance(self.default, float):
            return float(raw)  # type: ignore[return-value]
        return raw


def all_options() -> Mapping[str, ConfigOption[Any]]:
    """Registry of every declared option — the docs-generation seam
    (ref: flink-docs/ config option reference generator)."""
    return dict(_REGISTRY)


# Namespaces whose keys are legal without a per-key declaration — the
# plan analyzer's CONFIG_KEY_UNKNOWN rule and the repo lints treat any
# key under a declared prefix as grammatical. Use sparingly: a dynamic
# prefix trades per-key validation away for open-ended parameters.
_DYNAMIC_PREFIXES: Dict[str, str] = {}


def declare_dynamic_prefix(prefix: str, description: str = "") -> str:
    if not prefix.endswith("."):
        raise ValueError(f"dynamic prefix must end with '.': {prefix!r}")
    _DYNAMIC_PREFIXES[prefix] = description
    return prefix


def dynamic_prefixes() -> Mapping[str, str]:
    return dict(_DYNAMIC_PREFIXES)


def is_declared_key(key: str) -> bool:
    """True when ``key`` is part of the config grammar: a registered
    option or under a declared dynamic prefix."""
    return key in _REGISTRY or any(
        key.startswith(p) for p in _DYNAMIC_PREFIXES)


# test.* carries per-job parameters of the deployable test jobs
# (tests/runner_job*.py) through the submitted Configuration — the
# job-jar argument channel of the test harness.
declare_dynamic_prefix(
    "test.", "test-harness job parameters (tests/runner_job*.py)")


class Configuration:
    """Layered key→value store (ref: Configuration.java).

    Resolution order, lowest to highest precedence:
    option defaults < loaded file < ``FLINK_TPU_*`` env vars < ``set()``.
    """

    ENV_PREFIX = "FLINK_TPU_"

    def __init__(self, values: Optional[Mapping[str, Any]] = None) -> None:
        self._file: Dict[str, Any] = {}
        self._explicit: Dict[str, Any] = dict(values or {})

    # -- loading ---------------------------------------------------------
    @classmethod
    def from_file(cls, path: str) -> "Configuration":
        """Load ``key: value`` lines or a JSON object
        (ref: GlobalConfiguration.loadConfiguration)."""
        conf = cls()
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        stripped = text.lstrip()
        if stripped.startswith("{"):
            conf._file.update(json.loads(text))
            return conf
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                k, _, v = line.partition(":")
            elif "=" in line:
                k, _, v = line.partition("=")
            else:
                continue
            conf._file[k.strip()] = v.strip()
        return conf

    def _env_lookup(self, key: str) -> Optional[str]:
        env_key = self.ENV_PREFIX + key.upper().replace(".", "_").replace("-", "_")
        return os.environ.get(env_key)

    # -- access ----------------------------------------------------------
    def get(self, option: ConfigOption[T]) -> T:
        if option.key in self._explicit:
            return option._coerce(self._explicit[option.key])
        env = self._env_lookup(option.key)
        if env is not None:
            return option._coerce(env)
        if option.key in self._file:
            return option._coerce(self._file[option.key])
        return option.default

    def get_raw(self, key: str, default: Any = None) -> Any:
        if key in self._explicit:
            return self._explicit[key]
        env = self._env_lookup(key)
        if env is not None:
            return env
        return self._file.get(key, default)

    def set(self, option: "ConfigOption[T] | str", value: Any) -> "Configuration":
        key = option.key if isinstance(option, ConfigOption) else option
        self._explicit[key] = value
        return self

    def merged_with(self, other: "Configuration") -> "Configuration":
        out = Configuration()
        out._file = {**self._file, **other._file}
        out._explicit = {**self._explicit, **other._explicit}
        return out

    def keys(self) -> Iterator[str]:
        seen = set(self._file) | set(self._explicit)
        return iter(sorted(seen))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(self._file)
        out.update(self._explicit)
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"Configuration({self.to_dict()!r})"


def _parse_duration_ms(raw: str) -> int:
    """Parse '10 s', '500ms', '1 min' style durations to milliseconds
    (ref: flink-core/.../configuration/TimeUtils.java)."""
    raw = raw.strip().lower()
    units = [
        ("ms", 1),
        ("milliseconds", 1),
        ("s", 1000),
        ("sec", 1000),
        ("seconds", 1000),
        ("min", 60_000),
        ("minutes", 60_000),
        ("h", 3_600_000),
        ("hours", 3_600_000),
        ("d", 86_400_000),
        ("days", 86_400_000),
    ]
    # longest suffix match wins so "ms" is not parsed as "s"
    for suffix, mult in sorted(units, key=lambda u: -len(u[0])):
        if raw.endswith(suffix):
            num = raw[: -len(suffix)].strip()
            return int(float(num) * mult)
    return int(float(raw))


def duration_option(key: str, default_ms: int, description: str = "") -> ConfigOption[int]:
    return ConfigOption(key, default_ms, description, parse=_parse_duration_ms)


# ---------------------------------------------------------------------------
# Core option catalog (ref: TaskManagerOptions / CheckpointingOptions /
# ExecutionOptions catalogs in flink-core/.../configuration/).
# ---------------------------------------------------------------------------

class PipelineOptions:
    MICROBATCH_SIZE = ConfigOption(
        "pipeline.microbatch-size", 8192,
        "Records per device per step. Static shape under jit; the latency/"
        "throughput knob (the BufferDebloater analogue tunes it at runtime).")
    AUTO_WATERMARK_INTERVAL = duration_option(
        "pipeline.auto-watermark-interval", 200,
        "How often the host watermark clock advances (ref: "
        "pipeline.auto-watermark-interval).")
    OBJECT_REUSE = ConfigOption(
        "pipeline.object-reuse", True,
        "Reuse ingest buffers between steps (always safe here: device "
        "owns data after dispatch).")
    MAX_INFLIGHT_STEPS = ConfigOption(
        "pipeline.max-inflight-steps", 3,
        "Microbatch dispatches allowed in flight before ingest blocks on "
        "the oldest — bounds the transport/device queue so emit polls "
        "and checkpoints wait on at most this much backlog (the "
        "credit-based flow-control analogue: SPMD backpressure = step "
        "time; this is the credit count).")
    SOURCE_PREFETCH = ConfigOption(
        "pipeline.source-prefetch", 2,
        "Batches each source split pulls ahead on a feeder thread, so "
        "record generation/decode overlaps the loop's keying + transfer "
        "+ dispatch work (ref: the SourceReader split-fetcher thread "
        "model). 0 disables.")
    EXCHANGE_CAPACITY = ConfigOption(
        "pipeline.exchange-capacity", 0,
        "Per-(source, destination) bucket capacity of the keyBy "
        "all_to_all exchange, in records. Bounds the exchange buffer to "
        "devices x capacity per device. 0 = auto (per-device block "
        "size: can never overflow). When set, batches are SPLIT on the "
        "host so no bucket can exceed it — skewed keys cost extra "
        "steps, never data (ref: credit-based flow control's no-loss "
        "property, SURVEY §3.6).")
    EMIT_DEFER_MS = duration_option(
        "pipeline.emit-defer", -1,
        "How long the emit drain thread lets a fired batch age before "
        "fetching it, so the async device→host copy issued at dispatch "
        "completes in the background and the fetch is a local read "
        "instead of a blocking transfer (the latency/throughput knob of "
        "the emit path; ref role: BufferDebloater's in-flight target). "
        "-1 = auto: 0 on CPU hosts (device→host is a memcpy), 100ms on "
        "accelerator backends. A checkpoint barrier or end-of-input "
        "flush overrides the deferral immediately.")
    TARGET_LATENCY = duration_option(
        "pipeline.target-latency", 0,
        "Adaptive microbatch debloater (ref: BufferDebloater — auto-"
        "size in-flight buffers to hit a latency target): when > 0, the "
        "driver re-chunks source batches at ingest, halving the chunk "
        "while recent emit p99 exceeds the target and growing it back "
        "toward the source batch size while p99 sits under half the "
        "target. 0 = off (source batch size rules, maximum throughput).")
    SUB_BATCHES = ConfigOption(
        "pipeline.sub-batches", 1,
        "Chained sub-batch device programs per LOGICAL microbatch (the "
        "fire/emit decoupling knob, PROFILE.md §8.6): K > 1 splits each "
        "logical batch into K equal sub-batch steps with watermark "
        "advances, fire dispatches, and drain deliveries interleaved at "
        "sub-batch boundaries — a fired window's rows become "
        "host-visible at sub-batch cadence (~batch_wall/K) instead of "
        "full-batch cadence, while source positions, throttle probes, "
        "and checkpoint checks stay amortized at the logical-batch "
        "granularity. Must divide pipeline.microbatch-size (the plan "
        "analyzer rejects misconfigurations at submit, SUBBATCH_"
        "INVALID). 1 = the exact pre-split path. Committed output is "
        "byte-identical across K for exact lane monoids (counts, "
        "min/max, integer sums — the same contract as host.parallelism"
        "); float sums may differ in last-bit rounding because the "
        "device folds K partial batches instead of one.")
    FIRE_GATE = ConfigOption(
        "pipeline.fire-gate", True,
        "Fire-gated dispatch (PROFILE.md §12): the fused/devgen step "
        "programs run the fire/top-n/ring-append subgraph — and the "
        "pane purge — under a device-side conditional keyed on the "
        "dispatch header's window-end list, so a sub-batch in which no "
        "window can fire skips the dominant select sort instead of "
        "paying it every dispatch (the §8.6 sub-batch throughput tax). "
        "Committed output is byte-identical either way (the ungated "
        "subgraph is a provable no-op on a fireless step); false "
        "restores the unconditional pre-gate programs (the A/B axis).")
    READINESS = ConfigOption(
        "pipeline.readiness", "piggyback",
        "How ingest backpressure learns that an in-flight device step "
        "completed (PROFILE.md §8.3 lever a / §12). 'piggyback' "
        "(default): every fused/devgen dispatch announces a tiny "
        "per-step output (the devgen stats vector / the fused kernel's "
        "emit-ring head row) with copy_to_host_async at dispatch, and "
        "the throttle retires the step by CONSUMING that in-flight "
        "transfer — no separate is_ready control round trips, and the "
        "token's ring-head words stand in for a ring-header poll "
        "(opportunistic drains skip provably-empty fetches). 'probe': "
        "the legacy is_ready spin on the step's in-flight marker "
        "(zero per-step d2h traffic — the trade on transports where "
        "any per-step transfer costs in-situ service time).")
    PROFILE_DIR = ConfigOption(
        "pipeline.profile-dir", "",
        "When set, the driver wraps pipeline.profile-steps WARM logical "
        "batches (after pipeline.profile-skip) of the streaming run in "
        "jax.profiler.trace(dir) and writes a per-op device-time "
        "summary to <dir>/profile_summary.json (flink_tpu/obs/"
        "profiling.py; the summary also rides JobResult.metrics under "
        "'profile.trace_summary'). The first-class seam for naming "
        "per-op device costs that black-box bisection cannot (PROFILE."
        "md §8.5). Empty = off (zero overhead).")
    PROFILE_STEPS = ConfigOption(
        "pipeline.profile-steps", 8,
        "Logical batches captured inside the jax.profiler.trace window "
        "when pipeline.profile-dir is set.")
    PROFILE_SKIP = ConfigOption(
        "pipeline.profile-skip", 4,
        "Warm-up logical batches to run BEFORE the profiler trace "
        "starts (compile + cache warm-up must not pollute the per-op "
        "summary) when pipeline.profile-dir is set.")


class ExecutionOptions:
    RUNTIME_MODE = ConfigOption(
        "execution.runtime-mode", "streaming",
        "'streaming' (default): one pipelined region, per-microbatch "
        "watermark advance, continuous window fires. 'batch': bounded "
        "execution (ref: execution.runtime-mode=BATCH, SURVEY §3.7) — "
        "requires every source to report bounded=True; the compiler "
        "marks stage-boundary edges BLOCKING, stages run in topological "
        "waves (runtime/scheduler.py), each upstream stage materializes "
        "its full output to columnar partition files "
        "(exchange/blocking.py + formats_columnar.py), and stateful "
        "operators fire exactly once at end-of-input (no per-step fire "
        "scans). Recovery is re-execution: checkpointing/restore are "
        "rejected in this mode. Honest scope: no sort-merge spill, no "
        "speculative execution (SURVEY §3.7 SPMD rationale).")
    BATCH_SHUFFLE_DIR = ConfigOption(
        "execution.batch.shuffle-dir", "/tmp/flink-tpu-shuffle",
        "Root directory for blocking-shuffle partition files of batch "
        "(bounded-mode) jobs. Node-local scratch space — the analogue "
        "of io.tmp.dirs for BoundedBlockingSubpartition spill files; "
        "each run spools under a unique subdirectory.")
    BATCH_SHUFFLE_PARTITIONS = ConfigOption(
        "execution.batch.shuffle-partitions", 1,
        "Partition files per KEYED blocking edge: records hash-route "
        "by key (the same hash as the runtime exchange) so each file "
        "holds a disjoint key range, preserving per-key record order. "
        "Non-keyed edges always spool to a single file.")
    BATCH_SHUFFLE_CLEANUP = ConfigOption(
        "execution.batch.shuffle-cleanup", True,
        "Delete the run's shuffle spool directory when the job ends "
        "(success or failure). Set false to keep partition files for "
        "inspection.")


class LogOptions:
    DIR = ConfigOption(
        "log.dir", "/tmp/flink-tpu-log",
        "Root directory for embedded durable-log topics (flink_tpu/log/"
        "— the job-chaining exchange plane, the Kafka role without a "
        "broker process). LogSink.from_config resolves a topic name "
        "under this root; any registered FileSystem scheme works. Jobs "
        "chained through one topic must share this filesystem.")
    PARTITIONS = ConfigOption(
        "log.partitions", 1,
        "Default partition count for topics created by "
        "LogSink.from_config. Partitions are the source-split unit of "
        "LogSource (one replayable split per partition); records "
        "hash-route by the sink's key_field, so each partition holds a "
        "disjoint key range and per-key order is preserved. Fixed at "
        "topic creation — reopening with a different count fails "
        "loudly (offsets are per-partition).")
    SEGMENT_RECORDS = ConfigOption(
        "log.segment-records", 65536,
        "Records per appended log segment before the appender rolls to "
        "a new file within one transaction. Every segment is written "
        "sealed (columnar footer + fsync) at pre-commit, so this is "
        "also the recovery/replay granularity of a topic partition.")
    FSYNC_MODE = ConfigOption(
        "log.fsync-mode", "group",
        "Segment durability discipline at transaction pre-commit: "
        "'group' (default) writes every staged segment first and runs "
        "ONE group-commit fsync pass over all of them strictly before "
        "the pre-commit marker publishes (fsyncs overlap through the "
        "host pool on multi-partition topics); 'segment' is the legacy "
        "fsync-per-file-at-write discipline. The 2PC crash-window "
        "semantics are identical: the marker rename — the point after "
        "which a transaction is recoverable — always strictly follows "
        "every segment fsync.")
    ZERO_COPY = ConfigOption(
        "log.zero-copy", True,
        "LogSource decode mode: true mmaps sealed local-fs segments "
        "and returns fixed-width columns as read-only np.frombuffer "
        "views (no read() image copy, no per-column decode copy; "
        "block CRCs still verified, corruption/truncation exactly as "
        "loud). false is the legacy copying decode. Non-local schemes "
        "and big-endian hosts degrade to copying automatically.")
    READ_BATCH_RECORDS = ConfigOption(
        "log.read-batch-records", 262_144,
        "LogSource read-batch coalescing target: on-disk blocks merge "
        "until a batch holds at least this many rows before entering "
        "the pipeline — small sealed blocks otherwise starve the "
        "device path with tiny dispatches (the backfill bench's "
        "dominant cost on this container, PROFILE.md §11). Replay "
        "positions advance at merged-batch boundaries and stay "
        "checkpoint-exact. 0 = per-block reads (the legacy "
        "granularity).")
    PREFETCH_SEGMENTS = ConfigOption(
        "log.prefetch-segments", 1,
        "Merged read batches the LogSource decodes ahead on a feeder "
        "thread while the pipeline consumes the current one (the "
        "cluster.dcn-overlap shape at the segment-read seam; 1 = "
        "double-buffered). 0 disables — reads run inline on the "
        "consuming thread. Positions stay checkpoint-exact: only "
        "consumed batches advance them, a restore re-reads from the "
        "frozen offset.")
    COMPACTION_KEY_FIELD = ConfigOption(
        "log.compaction.key-field", "",
        "Key column for latest-wins key compaction (log/bus.py "
        "Compactor): sealed committed segments below the safety floor "
        "are rewritten keeping only the latest committed row per key, "
        "original offsets preserved. Empty = the key_field recorded in "
        "the topic's meta.json at creation (the sink's routing key).")
    COMPACTION_MIN_SEGMENTS = ConfigOption(
        "log.compaction.min-segments", 2,
        "Only compact a partition when at least this many sealed "
        "committed segments sit wholly below the safety floor — a "
        "single segment gains nothing from a rewrite; raising it "
        "amortizes rewrite I/O over more input (the Kafka "
        "min.cleanable.dirty.ratio role, count-based).")
    RETENTION_MS = ConfigOption(
        "log.retention.ms", 0,
        "Retention window: whole sealed segments whose newest row is "
        "older than this (by the topic's ts column) are dropped, but "
        "NEVER above the safety floor (lowest consumer-group committed "
        "offset / open pre-commit marker). 0 = keep forever.")
    RETENTION_TS_FIELD = ConfigOption(
        "log.retention.ts-field", "",
        "Event-time column used by log.retention.ms: a segment's age "
        "is now minus its newest row's value in this column. Required "
        "whenever log.retention.ms > 0 — a time-retention pass "
        "without it fails loudly (size-only retention leaves both "
        "unset).")
    RETENTION_BYTES = ConfigOption(
        "log.retention.bytes", 0,
        "Per-partition size budget: oldest whole sealed segments are "
        "dropped until the partition fits, subject to the same safety "
        "floor as log.retention.ms. 0 = unbounded.")
    LEASE_TTL_MS = ConfigOption(
        "log.lease.ttl-ms", 30_000,
        "Per-partition writer-lease time-to-live (log/bus.py "
        "LeaseManager). A producer renews its leases at every stage/"
        "commit; a lease this stale is expired and another producer "
        "may take the partition over with a bumped fencing epoch — "
        "the deposed holder's late writes are rejected by epoch.")
    GROUP_NAME = ConfigOption(
        "log.group.name", "",
        "Consumer-group name for LogSource.from_config: members share "
        "a topic with per-partition committed offsets published at "
        "checkpoint complete (the compaction/retention safety floor "
        "and the cross-generation resume point). Empty = no group "
        "(anonymous reader, offsets live only in the job checkpoint).")
    GROUP_MEMBER = ConfigOption(
        "log.group.member", 0,
        "This reader's member index within log.group.members: static "
        "partition assignment p % members == member (disjoint, "
        "deterministic — no broker to rebalance).")
    GROUP_MEMBERS = ConfigOption(
        "log.group.members", 1,
        "Total members in the consumer group; together with "
        "log.group.member this fixes the partition assignment. All "
        "members of one group must agree on this count.")
    GROUP_MEMBER_ID = ConfigOption(
        "log.group.member-id", "",
        "DYNAMIC membership: a non-empty id makes LogSource.from_config "
        "join the group's durable membership manifest at open "
        "(idempotent re-join on restart) and derive its partition "
        "assignment from the manifest's sorted member list — the "
        "generation-fenced rebalance protocol, instead of the static "
        "log.group.member/members pair. Offset commits are keyed by "
        "the joined generation; a member deposed by a rebalance it "
        "missed has its late commit rejected at the fence. Members "
        "leave explicitly (ConsumerGroups.leave / the log CLI), not on "
        "close — a restart must keep its seat.")
    CLEANER_ENABLED = ConfigOption(
        "log.cleaner.enabled", False,
        "Run the driver-owned background cleaner service "
        "(log/cleaner.py): one maintenance thread per log topic the "
        "job writes, executing compaction + retention per the "
        "log.compaction.*/log.retention.* grammar at "
        "log.cleaner.interval-ms cadence under a fenced cleaner lease "
        "and the per-topic maintenance lock. False (default) keeps "
        "maintenance an explicit CLI invocation (`log TOPIC --compact/"
        "--retain`).")
    CLEANER_INTERVAL_MS = ConfigOption(
        "log.cleaner.interval-ms", 30_000,
        "Cadence of the background cleaner's maintenance passes per "
        "topic (the Kafka log.cleaner backoff role). Each pass runs "
        "compaction then retention below the safety floor; readers "
        "and leased producers race it freely — the manifest-swap "
        "discipline keeps their reads byte-identical.")
    CLEANER_LEASE_TTL_MS = ConfigOption(
        "log.cleaner.lease-ttl-ms", 60_000,
        "Time-to-live of the fenced cleaner lease (cleaner.lease in "
        "the topic dir): exactly one cleaner service owns a topic's "
        "maintenance at a time, a crashed cleaner's lease expires "
        "after this, and a deposed cleaner's late pass dies at its "
        "next lease verify (the writer-lease epoch discipline).")


class CoreOptions:
    PLUGINS = ConfigOption(
        "plugins.modules", "",
        "Comma-separated module names loaded at environment creation; "
        "each must expose register(registry) extending the FileSystem "
        "scheme registry (ref: core/plugin/PluginManager + "
        "FileSystemFactory SPI; see flink_tpu/fs.py).")


class StateOptions:
    NUM_KEY_SHARDS = ConfigOption(
        "state.num-key-shards", 128,
        "Fixed hash space decoupling logical keys from devices — the "
        "maxParallelism / key-group analogue (ref: "
        "runtime/state/KeyGroupRangeAssignment.java, default 128). Must be "
        "a multiple of the mesh device count.")
    SLOTS_PER_SHARD = ConfigOption(
        "state.slots-per-shard", 4096,
        "Distinct keys a shard can hold before spill/eviction. "
        "slots*shards bounds resident key cardinality in HBM.")
    BACKEND = ConfigOption(
        "state.backend", "hbm",
        "Keyed state backend: 'hbm' (dense pane tensors, the "
        "HeapKeyedStateBackend analogue), 'spill' (RAM-resident host "
        "offload, the RocksDB analogue) or 'lsm' (disk-backed spill "
        "tier: memtable delta bounded by state.memory-budget-bytes, "
        "sealed into CRC'd columnar runs with changelog checkpoints — "
        "the RocksDB + flink-dstl analogue, flink_tpu/state/lsm.py).")
    MEMORY_BUDGET_BYTES = ConfigOption(
        "state.memory-budget-bytes", 64 * 1024 * 1024,
        "RAM ceiling for the in-memory delta (memtable) of the 'lsm' "
        "backend, per windowed operator; when the delta's pane tables "
        "exceed it, the delta is sealed into a sorted on-disk run. "
        "Ignored by 'hbm' and 'spill' (those hold all state resident). "
        "Must be at least state.lsm.run-floor-bytes.")
    LSM_DIR = ConfigOption(
        "state.lsm.dir", "/tmp/flink-tpu-state",
        "Root directory for 'lsm' backend run files; each operator "
        "instance gets a unique store subdirectory. Local filesystem "
        "only (runs are mmap'd for zero-copy scans).")
    LSM_COMPACT_MIN_RUNS = ConfigOption(
        "state.lsm.compact-min-runs", 4,
        "Sealed-run count that triggers a leveled compaction pass "
        "(k-way monoid merge of all live runs into one higher-level "
        "run, under the store's maintenance lock). Minimum 2.")
    LSM_RUN_FLOOR_BYTES = ConfigOption(
        "state.lsm.run-floor-bytes", 65536,
        "Smallest useful sealed-run size; a memory budget below this "
        "floor would seal degenerate runs on nearly every batch and is "
        "rejected at analysis time (STATE_BUDGET_INVALID).")
    ALLOW_DROPS = ConfigOption(
        "state.allow-drops", False,
        "When a key-directory shard fills under state.backend='hbm', "
        "the DEFAULT is to FAIL the job loudly (the reference degrades "
        "but never drops — RocksDB's role, SURVEY §3.4). Set true to "
        "instead drop overflow keys' records with accounting "
        "(records_dropped_full), or use state.backend='spill' for "
        "exact host-side degradation.")


class StorageOptions:
    """The durable-storage degradation grammar (flink_tpu/fs.py): how
    the FileSystem seam behaves when the disk itself fails under a
    write — the crash-consistency plane's runtime half."""

    ENOSPC_POLICY = ConfigOption(
        "storage.enospc-policy", "retry",
        "How a durable write seam (checkpoint persist, log segment "
        "stage, sink part write — everything routed through "
        "fs.write_atomic/enospc_retry) handles OSError(ENOSPC): "
        "'retry' (default) re-attempts the whole-file write with "
        "bounded backoff (retention/rotation may free space between "
        "attempts; every re-attempt counts on the "
        "storage.enospc_retries metric, exhausted budgets count toward "
        "execution.checkpointing.tolerable-failures like any persist "
        "failure) or 'fail' (propagate immediately). Either way the "
        "tmp+fsync+rename discipline guarantees no torn file at a "
        "final name.")
    ENOSPC_RETRIES = ConfigOption(
        "storage.enospc-retries", 4,
        "Bounded retry budget per whole-file write under "
        "storage.enospc-policy=retry (0 behaves like 'fail').")
    ENOSPC_BACKOFF_MS = ConfigOption(
        "storage.enospc-backoff-ms", 50.0,
        "First retry delay in ms under storage.enospc-policy=retry; "
        "doubles per attempt.")


class CheckpointingOptions:
    INTERVAL = duration_option(
        "execution.checkpointing.interval", 0,
        "Checkpoint period in ms; 0 disables (ref: "
        "execution.checkpointing.interval).")
    DIRECTORY = ConfigOption(
        "execution.checkpointing.dir", "/tmp/flink-tpu-checkpoints",
        "Checkpoint storage root (ref: state.checkpoints.dir).")
    RETAINED = ConfigOption(
        "execution.checkpointing.num-retained", 3,
        "Completed checkpoints kept (ref: state.checkpoints.num-retained).")
    INCREMENTAL = ConfigOption(
        "execution.checkpointing.incremental", True,
        "Reuse (hardlink) the previous checkpoint's blob for operators "
        "whose state_version is unchanged — the RocksDB shared-SST "
        "analogue (checkpoint/storage.py format v2). False forces full "
        "re-serialization every checkpoint.")
    COMPRESSION = ConfigOption(
        "execution.checkpointing.compression", "none",
        "Compress checkpoint payload files: 'none' or 'zlib' (ref: "
        "execution.checkpointing.snapshot-compression). Applied on the "
        "background checkpoint executor, never the ingest loop; "
        "recorded in the manifest so restore self-describes.")
    RESTORE = ConfigOption(
        "execution.checkpointing.restore", "",
        "'' (fresh start), 'latest' (resume from newest complete "
        "checkpoint), or a checkpoint/savepoint directory path (ref: "
        "execution.savepoint.path).")
    TOLERABLE_FAILURES = ConfigOption(
        "execution.checkpointing.tolerable-failures", 0,
        "Consecutive PERIODIC checkpoint persist/commit failures the "
        "job rides out before failing over (ref: execution.checkpointing"
        ".tolerable-failed-checkpoints, default 0 = any failure fails "
        "the job). A tolerated epoch stays staged in its 2PC sinks and "
        "commits with the next successful checkpoint — exactly-once is "
        "unaffected. Savepoints and the final end-of-input checkpoint "
        "are never tolerated. Single-process driver only: the "
        "cross-host (DCN) step loop treats any checkpoint failure as "
        "an attempt failure — its rendezvous-consensus cut has no "
        "per-process skip, so recovery goes through restore.")


class ClusterOptions:
    MESH_DEVICES = ConfigOption(
        "cluster.mesh-devices", "",
        "Operator parallelism over a 1-D jax.sharding.Mesh: '' = "
        "single-device local execution, 'all' = every visible device, "
        "an integer N = the first N devices. Each device owns "
        "num-key-shards/N contiguous key shards (the key-group range of "
        "its 'subtask'); keyed exchanges ride XLA all_to_all over the "
        "mesh axis (ref: parallelism.default + slot assignment, "
        "KeyGroupRangeAssignment).")
    NUM_PROCESSES = ConfigOption(
        "cluster.num-processes", 1,
        "Host-process count of ONE job (the cross-host data plane, ref "
        "SURVEY §3.6): each process owns num-key-shards/N contiguous "
        "key shards; keyed records route to their owner through the "
        "per-step DCN all-to-all (exchange/dcn.py), whose rendezvous "
        "also carries the global watermark, termination, and "
        "checkpoint-alignment consensus.")
    PROCESS_ID = ConfigOption(
        "cluster.process-id", 0,
        "This process's index in [0, cluster.num-processes).")
    DCN_PEERS = ConfigOption(
        "cluster.dcn-peers", "",
        "Comma-separated host:port of every process's exchange "
        "listener, indexed by process id (the coordinator fills this "
        "at deploy via the dcn rendezvous; tests set it directly).")
    DCN_PORT = ConfigOption(
        "cluster.dcn-port", 0,
        "This process's exchange listen port (0 = ephemeral).")
    DCN_SECRET = ConfigOption(
        "cluster.dcn-secret", "",
        "Per-job shared secret authenticating the DCN exchange "
        "handshake (HMAC over the hello; exchange/dcn.py). The "
        "coordinator mints one per attempt and ships it in the deploy "
        "config; static cluster.dcn-peers deployments set it "
        "themselves. Empty = unauthenticated (single-host loopback "
        "only).")
    DCN_OVERLAP = ConfigOption(
        "cluster.dcn-overlap", True,
        "Step-overlapped cross-host exchange (exchange/dcn.py "
        "exchange_async): the driver dispatches step N+1's frames and "
        "consumes step N's at the NEXT iteration, so the N-way "
        "rendezvous overlaps the device compute and the host "
        "ingest/route work of the following step instead of "
        "serializing with them. Committed output is identical — the "
        "barrier moves, the per-step consensus (watermark/termination/"
        "checkpoint) does not. False = consume at dispatch (the v0 "
        "lockstep loop; one step of extra exchange latency saved per "
        "barrier, useful when bisecting the exchange itself).")
    DCN_OVERLAP_DRAIN = ConfigOption(
        "cluster.dcn-overlap-drain", True,
        "Drain the ONE in-flight overlapped exchange step before "
        "snapshotting at a checkpoint barrier (the default, and the "
        "exactly-once contract: the cut covers every routed record). "
        "False skips the drain — the snapshot's source positions then "
        "include a step whose records are still on the wire, so a "
        "restore from that checkpoint LOSES them (at-most-once for "
        "that step). Only for pipelines that tolerate loss; the plan "
        "analyzer flags it (DCN_OVERLAP_UNSAFE).")
    DCN_IO_THREADS = ConfigOption(
        "cluster.dcn-io-threads", 0,
        "Sender-worker threads of the parallel DCN I/O plane. 0 = "
        "auto (one per peer — all N-1 sends overlap). A positive "
        "value caps the workers; peers are assigned round-robin and "
        "stick to one worker so per-peer frame order stays FIFO. "
        "Receive threads are always per-peer (each blocks on its own "
        "socket; they are the step barrier).")
    DCN_BUFFER_BYTES = ConfigOption(
        "cluster.dcn-buffer-bytes", 0,
        "SO_SNDBUF/SO_RCVBUF for every DCN exchange socket, in bytes. "
        "0 = OS default. Raise it (e.g. 4-16 MB) on high-bandwidth-"
        "delay cross-rack links so one step's frames fit in the "
        "kernel buffers and the sender workers never stall mid-step.")
    DCN_BIND = ConfigOption(
        "cluster.dcn-bind", "auto",
        "Address the exchange listener binds. 'auto' (default) stays "
        "on 127.0.0.1 unless the configured peers (cluster.dcn-peers / "
        "cluster.dcn-host) are off-host, then widens to 0.0.0.0; set "
        "an explicit address to override.")
    EXCHANGE_IMPL = ConfigOption(
        "exchange.impl", "all-to-all",
        "Keyed-exchange collective pattern (the Shuffle SPI seam, ref: "
        "runtime/shuffle ShuffleMaster/ShuffleEnvironment): "
        "'all-to-all' = one fused lax.all_to_all (bandwidth-optimal on "
        "a fully-connected ICI axis); 'ring' = N-1 lax.ppermute "
        "neighbor hops (ring-only topologies / per-hop overlap). "
        "Third-party implementations register via "
        "exchange.spi.register_shuffle.")
    HEARTBEAT_INTERVAL = duration_option(
        "heartbeat.interval", 10_000,
        "Runner→coordinator heartbeat period (ref: heartbeat.interval=10s).")
    HEARTBEAT_TIMEOUT = duration_option(
        "heartbeat.timeout", 50_000,
        "Declare a runner dead after this silence (ref: heartbeat.timeout=50s).")
    # -- deploy-injected identity keys (the TaskDeploymentDescriptor
    # analogue): the coordinator/runner stamp these into the attempt's
    # config at deploy; user configs normally never set them.
    ATTEMPT = ConfigOption(
        "cluster.attempt", 0,
        "This attempt's fencing epoch, minted by the coordinator on "
        "every (re)deploy. Qualifies in-progress artifacts — "
        "chk-<id>.e<epoch> checkpoints, part-file and log-segment "
        "names — so a deposed attempt can never clobber a successor.")
    COORDINATOR = ConfigOption(
        "cluster.coordinator", "",
        "HOST:PORT of the job coordinator's RPC server, injected by the "
        "runner at deploy (split enumeration, savepoint reporting).")
    JOB_ID = ConfigOption(
        "cluster.job-id", "",
        "Submitted job id, injected by the runner at deploy.")
    RUNNER_ID = ConfigOption(
        "cluster.runner-id", "",
        "This runner's id, injected at deploy (coordinator-side split "
        "enumeration keys on it).")
    DCN_HOST = ConfigOption(
        "cluster.dcn-host", "",
        "Advertised host of this process's DCN exchange listener "
        "(coordinator-brokered rendezvous; defaults to the RPC-visible "
        "address when empty).")
    DCN_RENDEZVOUS = ConfigOption(
        "cluster.dcn-rendezvous", "",
        "'coordinator' lets a multi-process job discover DCN peers "
        "through the coordinator instead of a static cluster.dcn-peers "
        "list; stamped into the attempt config at deploy.")
    RESCALE_FROM = ConfigOption(
        "cluster.rescale-from", "",
        "Deploy-injected by the coordinator after a process-level "
        "rescale: the savepoint path (p0's, for multi-process "
        "savepoints) the new topology was restored from. When a later "
        "attempt restores with execution.checkpointing.restore=latest "
        "and finds NO checkpoint newer than this savepoint (or none at "
        "all — the crash landed before the first post-rescale "
        "checkpoint published), the driver falls back to this path so "
        "recovery never resurrects a pre-rescale checkpoint written "
        "for the OLD key-group ownership, and never replays from "
        "scratch duplicating committed output. User configs never set "
        "it.")
    RESTART_STRATEGY = ConfigOption(
        "restart-strategy.type", "exponential-delay",
        "fixed-delay | exponential-delay | failure-rate | none (ref: "
        "runtime/executiongraph/failover restart strategies).")
    RESTART_ATTEMPTS = ConfigOption(
        "restart-strategy.fixed-delay.attempts", 3,
        "Max restarts for fixed-delay strategy.")
    RESTART_DELAY = duration_option(
        "restart-strategy.fixed-delay.delay", 1_000,
        "Delay between restarts for fixed-delay strategy.")


class HostOptions:
    PARALLELISM = ConfigOption(
        "host.parallelism", min(4, os.cpu_count() or 1),
        "Worker threads of the driver's shared host pool "
        "(flink_tpu/parallel/hostpool.py) running the host-resident "
        "operator paths: the key-sharded session span registry, the "
        "pane-partitioned spill store, and the chunked windowAll fold "
        "(PROFILE.md §9). 1 = the exact serial path (no pool threads; "
        "keeps single-core benchmark numbers reproducible). Default "
        "min(4, os.cpu_count()); the plan analyzer warns on values < 1 "
        "or beyond os.cpu_count() (HOST_PARALLELISM_INVALID).")
    FOLD_CHUNK_RECORDS = ConfigOption(
        "host.fold-chunk-records", 1 << 18,
        "Batch-size floor (and chunk size) of the host spill store's "
        "tree-reduction fold: batches below it absorb in one pass "
        "(pool dispatch overhead would exceed the fold, PROFILE.md "
        "§9.2); at or above it the batch splits into chunks of this "
        "many records whose pane partials combine in chunk order. The "
        "chunk size is independent of host.parallelism, so the "
        "reduction tree — and the output bytes — do not change with "
        "the worker count.")


class SessionOptions:
    """Session-cluster runtime mode (runtime/session.py, PAPER §3.4
    dispatcher / ResourceManager / slot pool + §4 session deployment):
    a long-lived SessionDispatcher multiplexes N submitted jobs onto a
    shared runner fleet through logical slot quotas, with fair drain
    scheduling and per-job isolation of checkpoints/faults/metrics."""

    SLOTS_PER_JOB = ConfigOption(
        "session.slots-per-job", 1,
        "Logical slots ONE job occupies on its runner (the slot-sharing "
        "group size, ref: taskmanager slot model). A job may raise it "
        "in its own submitted config to claim a bigger share; admission "
        "rejects values < 1 or above session.runner-slots (a quota no "
        "single runner can ever satisfy — SESSION_QUOTA_INVALID flags "
        "both at analyze time).")
    RUNNER_SLOTS = ConfigOption(
        "session.runner-slots", 4,
        "Logical slot capacity each registered runner contributes to "
        "the session slot pool (ref: taskmanager.numberOfTaskSlots). "
        "Per RUNNER HOST, not per device: the session plane shares one "
        "chip/host among jobs — device-exclusive placement stays the "
        "per-job (non-session) submit path.")
    MAX_JOBS = ConfigOption(
        "session.max-jobs", 8,
        "Maximum jobs RUNNING concurrently across the session cluster; "
        "submissions beyond it queue FIFO and deploy as running jobs "
        "finish (the Dispatcher submission queue). Queued depth feeds "
        "the autoscaler.")
    FAIR_DRAIN = ConfigOption(
        "session.fair-drain", False,
        "Serialize co-resident jobs' emit-ring drain fetches through a "
        "round-robin turnstile (runtime/session.py FairDrainGate) so "
        "one job's fire/drain burst cannot starve another's emit ring "
        "on the shared device→host link. The dispatcher stamps this "
        "true into every session deploy; single-job (non-session) runs "
        "default off and pay zero overhead.")
    CONCURRENT_JOBS = ConfigOption(
        "session.concurrent-jobs", 1,
        "Deploy-injected by the SessionDispatcher: the job's STATIC "
        "slot-proportional share denominator — how many jobs of its "
        "quota fit one runner (session.runner-slots // session.slots-"
        "per-job, clamped by session.max-jobs). The driver divides "
        "its host-pool worker count and in-flight step credit by it, "
        "so K co-resident tenants can never oversubscribe the host "
        "K-fold regardless of deploy order (the reference's per-slot "
        "managed-memory split discipline). User configs normally "
        "never set it.")
    SCOPED_FAULTS = ConfigOption(
        "session.scoped-faults", False,
        "Deploy-injected by the SessionDispatcher when a session job "
        "carries a faults.* plan: the runner installs it as a JOB-"
        "SCOPED plan (faults.install_scoped) instead of the process-"
        "global one, so one tenant's chaos schedule can never inject "
        "into a co-resident job (the per-job fault-plan isolation of "
        "the session contract).")
    AUTOSCALE = ConfigOption(
        "session.autoscale", True,
        "Run the dispatcher's autoscaler loop: submission-queue depth "
        "and aggregate slot pressure push scale-OUT demand through the "
        "provisioner seam (runtime/provisioner.py request_capacity); "
        "runners idle past session.scale-down-idle above session.min-"
        "runners drain (stop-with-savepoint redeploy) and are released "
        "(release_capacity). False = fixed fleet.")
    AUTOSCALE_INTERVAL = duration_option(
        "session.autoscale-interval", 2_000,
        "Autoscaler evaluation period.")
    MIN_RUNNERS = ConfigOption(
        "session.min-runners", 1,
        "Floor the autoscaler never drains below.")
    MAX_RUNNERS = ConfigOption(
        "session.max-runners", 8,
        "Ceiling on the runner fleet the autoscaler will request "
        "capacity for (scale-out demand is clamped here, mirroring the "
        "provisioner's own max_replicas guard).")
    SCALE_DOWN_IDLE = duration_option(
        "session.scale-down-idle", 30_000,
        "A runner holding zero session slots for this long (with the "
        "fleet above session.min-runners) is drained and released by "
        "the autoscaler.")
    HA_STANDBY = ConfigOption(
        "session.ha.standby", False,
        "Start this `session start` process as a hot-standby contender "
        "(the --standby flag sets it): it contends for the leadership "
        "lease in high-availability.dir and serves only once granted — "
        "on takeover it re-hydrates the durable session registry, "
        "re-queues undeployed jobs in original FIFO order, and waits "
        "for runners to re-attach their live executions. Requires "
        "high-availability.dir.")
    HA_REATTACH_GRACE = duration_option(
        "session.ha.reattach-grace", 10_000,
        "How long a new leader waits for a recovered RUNNING job's "
        "runner to re-register carrying it before falling back to a "
        "blind redeploy with restore:latest. A stored runner that "
        "re-registers WITHOUT the job collapses the window early (the "
        "execution died there); a runner that re-attaches it ends the "
        "wait with an in-place re-adoption (no redeploy, exactly-once "
        "preserved). Lower it when runners re-resolve the leader fast "
        "(small heartbeat.interval); raise it on congested fleets "
        "where a blind double-deploy is costlier than a slow failover.")


class RescaleOptions:
    """Reactive elastic rescaling (runtime/coordinator.py, ref: the
    AdaptiveScheduler / reactive mode, FLIP-159/160): the coordinator
    watches the heartbeat-carried backpressure/drain gauges and, when
    pressure stays outside the configured band for a sustained window,
    arms the SAME stop-with-savepoint → repartition → redeploy
    handshake `rescale JOB --devices N` drives manually. Key-group
    discipline (state.num-key-shards at a fixed max-parallelism) makes
    the N→M state move legal; cooldown + the two-sided band give
    hysteresis, so the controller cannot flap by construction."""

    MODE = ConfigOption(
        "rescale.mode", "off",
        "'off' (default) = rescale only via the manual RPC/CLI; "
        "'reactive' = the coordinator's policy loop arms rescales "
        "automatically from observed pressure. Reactive mode requires "
        "checkpointing (the handshake is savepoint-based) — the plan "
        "analyzer rejects it otherwise (RESCALE_INVALID).")
    TARGET_PRESSURE_HIGH = ConfigOption(
        "rescale.target-pressure-high", 70,
        "Upper bound of the target pressure band, in percent of the "
        "job's max(backpressure_pct, drain_busy_pct) heartbeat gauge. "
        "Pressure sustained ABOVE it arms a scale-OUT to the next "
        "legal width (divisibility-preserving doubling, clamped by "
        "rescale.max-devices).")
    TARGET_PRESSURE_LOW = ConfigOption(
        "rescale.target-pressure-low", 20,
        "Lower bound of the band: pressure sustained BELOW it arms a "
        "scale-IN to the previous legal width (halving, floored at "
        "rescale.min-devices). The gap between low and high is the "
        "hysteresis dead zone — a signal oscillating inside it never "
        "triggers.")
    SUSTAINED_WINDOW = duration_option(
        "rescale.sustained-window", 30_000,
        "How long pressure must stay continuously outside the band "
        "before the controller arms a rescale. One in-band sample "
        "resets the clock, so transient spikes (a slow checkpoint, a "
        "GC pause) never rescale the job.")
    COOLDOWN = duration_option(
        "rescale.cooldown", 120_000,
        "Minimum time between controller-armed rescales of one job, "
        "measured from the last rescale COMPLETING (redeploy at the "
        "new width). Keep it above the checkpoint interval — a "
        "cooldown shorter than execution.checkpointing.interval "
        "re-arms before the first post-rescale checkpoint publishes "
        "(RESCALE_INVALID warns).")
    MIN_DEVICES = ConfigOption(
        "rescale.min-devices", 1,
        "Floor the reactive controller never scales below.")
    MAX_DEVICES = ConfigOption(
        "rescale.max-devices", 0,
        "Ceiling the reactive controller never scales above. 0 = the "
        "job's current fleet capacity (largest registered runner).")


class AnalysisOptions:
    FAIL_ON = ConfigOption(
        "analysis.fail-on", "error",
        "Compile-time plan analysis at submit (flink_tpu/analysis/): "
        "'error' (default) fails the job when any error-severity "
        "finding fires (misconfigurations that WILL break at runtime: "
        "unbounded source in batch mode, two log writers on one topic, "
        "fault rules matching no registered point); 'warn' also fails "
        "on warn-severity findings (correctness smells: event-time "
        "windows without a watermark strategy, non-transactional sinks "
        "under checkpointing, unknown config keys); 'off' skips "
        "analysis entirely. Findings below the threshold are kept on "
        "the driver (driver.analysis_findings) without failing the "
        "job. `python -m flink_tpu analyze` runs the same rules "
        "standalone.")
    MAX_STATE_BYTES_PER_KEY = ConfigOption(
        "analysis.max-state-bytes-per-key", 0,
        "Per-key state budget in BYTES for the analyzer's dataflow "
        "plane (analysis/dataflow.py): when > 0, any stateful operator "
        "whose statically-estimated per-key state footprint (lane "
        "accumulators x live panes, from the window/lateness geometry) "
        "exceeds it raises a STATE_BYTES_EXCEEDED warning at submit — "
        "the admission-control seam for multi-tenant budgeting (the "
        "same estimate `analyze --explain` prints per node). 0 = off. "
        "Estimates cover the dense lane layouts; element-buffer "
        "operators (evictors, CEP partial matches) are data-dependent "
        "and never flagged.")


class SourceOptions:
    ENUMERATION = ConfigOption(
        "source.enumeration", "local",
        "Split ownership: 'local' = this process reads every split "
        "(single-runner execution); 'coordinator' = ask the job "
        "coordinator's split enumerator for this runner's share, so "
        "multiple runners of one job divide the source without overlap "
        "(ref: FLIP-27 SplitEnumerator on the JobMaster / "
        "SourceCoordinator). Requires cluster.coordinator/job-id/"
        "runner-id, which the runner injects on deploy.")


class MemoryOptions:
    HBM_BUDGET = ConfigOption(
        "memory.hbm-budget", 0,
        "Plan-time PER-DEVICE HBM budget in BYTES for device-resident "
        "operator state (pane tensors, emit rings). HBM is a per-chip "
        "resource and state shards one block per device, so the check "
        "is per-device and independent of mesh width. Dense static "
        "layouts make the footprint computable before the first step — "
        "a job that cannot fit fails at build with a per-operator "
        "breakdown instead of an XLA allocator error mid-run (ref: "
        "MemoryManager managed-memory budgeting). 0 = unlimited.")


class HighAvailabilityOptions:
    HA_DIR = ConfigOption(
        "high-availability.dir", "",
        "Shared directory for leader election + the job graph store. "
        "Empty = HA off. A standby coordinator pointed at the same dir "
        "takes leadership when the incumbent's lease lapses and "
        "recovers every non-terminal job from the store (ref: "
        "runtime/highavailability HighAvailabilityServices + "
        "JobGraphStore + leader election via ZooKeeper/K8s; here the "
        "shared filesystem is the consensus substrate).")
    LEASE_TIMEOUT = duration_option(
        "high-availability.lease-timeout", 10_000,
        "Leadership lease: the leader renews within this period; a "
        "contender may claim a lease older than this (ref: ZooKeeper "
        "session timeout role).")
