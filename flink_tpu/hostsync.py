"""Host-side completion waiting for device values.

``jax.block_until_ready`` on some PJRT backends — measured on the
remote-attached TPU this framework targets — parks the waiting thread
on a coarse completion-poll quantum (~50ms per wait) whenever the value
is not yet ready; the same is true for an unannounced ``np.asarray``
device→host fetch (~90ms fixed). A cooperative ``is_ready()`` spin with
a short sleep observes completion at millisecond granularity instead
(measured 1.4ms vs 56ms per throttled step on the same pipeline).

Every hot-path wait in the runtime goes through ``ready_wait``; cold
paths (tests, shutdown) may keep ``block_until_ready``.
"""
from __future__ import annotations

import time

import jax

# 2ms: well under the per-microbatch budget, far over the ~0.4us cost
# of an is_ready() probe
POLL_S = 0.002


def ready_wait(x, poll_s: float = POLL_S):
    """Wait until every array leaf of ``x`` is ready, without parking
    the thread on the backend's coarse blocking-wait quantum. Returns
    ``x`` for chaining."""
    for leaf in jax.tree_util.tree_leaves(x):
        is_ready = getattr(leaf, "is_ready", None)
        if is_ready is None:
            continue
        try:
            while not is_ready():
                time.sleep(poll_s)
        except RuntimeError:
            # deleted/donated buffers surface here; the caller's next
            # use raises the real error with context
            return x
    return x
