"""Window assigners, windows, and triggers — the north-star API surface.

ref: streaming/api/windowing/assigners/{WindowAssigner,
TumblingEventTimeWindows,SlidingEventTimeWindows,EventTimeSessionWindows,
GlobalWindows}.java, windows/TimeWindow.java, triggers/{Trigger,
EventTimeTrigger,CountTrigger,PurgingTrigger}.java.

TPU-first redesign: time windows are **pane-decomposed** up front. The
reference's DataStream ``WindowOperator`` writes every element into each
overlapping window's state (a Q5 10s/1s sliding window costs 10 state
writes per element); the Table runtime's slicing optimization
(flink-table-runtime .../operators/window/ SliceAssigner) aggregates each
element once per non-overlapping slice and combines slices at fire time.
Here slicing is the *only* mode: an assigner exposes ``pane_ms`` (the
slice), every element is scatter-added into exactly one ``(key, pane)``
cell, and a window is a contiguous run of ``panes_per_window`` panes —
which is what makes the whole thing one dense tensor op on the MXU/VPU.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

from flink_tpu.records import MIN_TS


@dataclasses.dataclass(frozen=True, order=True)
class TimeWindow:
    """[start, end) window in epoch ms (ref: windows/TimeWindow.java)."""

    start: int
    end: int

    def max_timestamp(self) -> int:
        return self.end - 1

    def __repr__(self) -> str:
        return f"TimeWindow[{self.start}, {self.end})"


class WindowAssigner:
    """Base assigner. Pane-decomposable assigners (all time windows)
    report a pane length and window composition; session windows are
    merging and handled by the session registry instead.
    """

    is_event_time: bool = True
    is_merging: bool = False

    @property
    def pane_ms(self) -> int:
        raise NotImplementedError

    @property
    def size_ms(self) -> int:
        raise NotImplementedError

    @property
    def slide_ms(self) -> int:
        raise NotImplementedError

    @property
    def offset_ms(self) -> int:
        return 0

    @property
    def panes_per_window(self) -> int:
        return self.size_ms // self.pane_ms

    @property
    def panes_per_slide(self) -> int:
        return self.slide_ms // self.pane_ms

    def pane_index(self, timestamp: int) -> int:
        """Global pane id of a timestamp (device version lives in
        ops/window.py; both must agree)."""
        return (timestamp - self.offset_ms) // self.pane_ms

    def window_for_end_pane(self, end_pane: int) -> TimeWindow:
        end = end_pane * self.pane_ms + self.offset_ms
        return TimeWindow(end - self.size_ms, end)

    def assign_windows(self, timestamp: int) -> list[TimeWindow]:
        """Host/reference-semantics path (harness tests golden-check the
        device kernels against this; ref: WindowAssigner.assignWindows)."""
        if timestamp == MIN_TS:
            return []
        last_start = timestamp - (timestamp - self.offset_ms) % self.slide_ms
        out = []
        start = last_start
        while start > timestamp - self.size_ms:
            out.append(TimeWindow(start, start + self.size_ms))
            start -= self.slide_ms
        return list(reversed(out))


@dataclasses.dataclass(frozen=True)
class TumblingEventTimeWindows(WindowAssigner):
    """ref: assigners/TumblingEventTimeWindows.java"""

    size: int
    offset: int = 0

    @classmethod
    def of(cls, size_ms: int, offset_ms: int = 0) -> "TumblingEventTimeWindows":
        return cls(size_ms, offset_ms)

    @property
    def pane_ms(self) -> int:
        return self.size

    @property
    def size_ms(self) -> int:
        return self.size

    @property
    def slide_ms(self) -> int:
        return self.size

    @property
    def offset_ms(self) -> int:
        return self.offset


@dataclasses.dataclass(frozen=True)
class SlidingEventTimeWindows(WindowAssigner):
    """ref: assigners/SlidingEventTimeWindows.java — but lowered to panes
    (slices), NOT per-window state writes; see module docstring."""

    size: int
    slide: int
    offset: int = 0

    @classmethod
    def of(cls, size_ms: int, slide_ms: int, offset_ms: int = 0) -> "SlidingEventTimeWindows":
        return cls(size_ms, slide_ms, offset_ms)

    def __post_init__(self) -> None:
        if self.size <= 0 or self.slide <= 0:
            raise ValueError("size and slide must be positive")

    @property
    def pane_ms(self) -> int:
        return math.gcd(self.size, self.slide)

    @property
    def size_ms(self) -> int:
        return self.size

    @property
    def slide_ms(self) -> int:
        return self.slide

    @property
    def offset_ms(self) -> int:
        return self.offset


@dataclasses.dataclass(frozen=True)
class TumblingProcessingTimeWindows(WindowAssigner):
    """Tumbling windows over PROCESSING time (ref: assigners/
    TumblingProcessingTimeWindows.java). Records are assigned by the
    operator's clock at ingest, and firing is driven by the same clock
    advancing between steps — the pane machinery is identical to the
    event-time assigners, with arrival time as the time axis (so there
    is no lateness and no out-of-orderness by construction)."""

    size: int
    offset: int = 0
    is_event_time = False
    is_processing_time = True

    @classmethod
    def of(cls, size_ms: int, offset_ms: int = 0) -> "TumblingProcessingTimeWindows":
        return cls(size_ms, offset_ms)

    @property
    def pane_ms(self) -> int:
        return self.size

    @property
    def size_ms(self) -> int:
        return self.size

    @property
    def slide_ms(self) -> int:
        return self.size

    @property
    def offset_ms(self) -> int:
        return self.offset


@dataclasses.dataclass(frozen=True)
class SlidingProcessingTimeWindows(WindowAssigner):
    """ref: assigners/SlidingProcessingTimeWindows.java — pane-lowered
    like SlidingEventTimeWindows, over the processing-time axis."""

    size: int
    slide: int
    offset: int = 0
    is_event_time = False
    is_processing_time = True

    @classmethod
    def of(cls, size_ms: int, slide_ms: int,
           offset_ms: int = 0) -> "SlidingProcessingTimeWindows":
        return cls(size_ms, slide_ms, offset_ms)

    def __post_init__(self) -> None:
        if self.size <= 0 or self.slide <= 0:
            raise ValueError("size and slide must be positive")

    @property
    def pane_ms(self) -> int:
        return math.gcd(self.size, self.slide)

    @property
    def size_ms(self) -> int:
        return self.size

    @property
    def slide_ms(self) -> int:
        return self.slide

    @property
    def offset_ms(self) -> int:
        return self.offset


@dataclasses.dataclass(frozen=True)
class EventTimeSessionWindows(WindowAssigner):
    """Gap-merged sessions (ref: assigners/EventTimeSessionWindows.java,
    runtime merge logic in MergingWindowSet.java). Dynamic merging cannot
    be a static pane layout; the session operator keeps a host-side span
    registry and device-side per-span accumulators (SURVEY §8.4 item 3).
    """

    gap: int
    is_merging = True

    @classmethod
    def with_gap(cls, gap_ms: int) -> "EventTimeSessionWindows":
        return cls(gap_ms)

    @property
    def pane_ms(self) -> int:
        raise TypeError("session windows are not pane-decomposable")


@dataclasses.dataclass(frozen=True)
class GlobalWindows(WindowAssigner):
    """One eternal window; only fires via a (count/custom) trigger
    (ref: assigners/GlobalWindows.java)."""

    is_event_time = False

    @classmethod
    def create(cls) -> "GlobalWindows":
        return cls()

    @property
    def pane_ms(self) -> int:
        raise TypeError("global windows are not pane-decomposable")


# ---------------------------------------------------------------------------
# Triggers. ref: triggers/Trigger.java — onElement/onEventTime/
# onProcessingTime returning CONTINUE/FIRE/PURGE/FIRE_AND_PURGE.
#
# TPU lowering: EventTimeTrigger is evaluated as a vectorized mask over
# (key, pane) cells per watermark advance (no per-key callbacks);
# CountTrigger compares the always-present count lane against the
# threshold at step granularity.
# ---------------------------------------------------------------------------

class TriggerResult:
    CONTINUE = "CONTINUE"
    FIRE = "FIRE"
    PURGE = "PURGE"
    FIRE_AND_PURGE = "FIRE_AND_PURGE"


class Trigger:
    def on_element(self, timestamp: int, window: TimeWindow, count: int) -> str:
        return TriggerResult.CONTINUE

    def on_event_time(self, time: int, window: TimeWindow) -> str:
        return TriggerResult.CONTINUE

    def fires_on_watermark(self) -> bool:
        """Whether the device fire-mask path applies (event-time family)."""
        return False


class EventTimeTrigger(Trigger):
    """FIRE when watermark passes window.max_timestamp
    (ref: triggers/EventTimeTrigger.java)."""

    @classmethod
    def create(cls) -> "EventTimeTrigger":
        return cls()

    def on_event_time(self, time: int, window: TimeWindow) -> str:
        return TriggerResult.FIRE if time >= window.max_timestamp() else TriggerResult.CONTINUE

    def fires_on_watermark(self) -> bool:
        return True


class ProcessingTimeTrigger(Trigger):
    """FIRE when the processing-time clock passes window.max_timestamp
    (ref: triggers/ProcessingTimeTrigger.java). The default trigger of
    the processing-time assigners; evaluated as the same vectorized
    fire mask as EventTimeTrigger, over the clock instead of the
    watermark."""

    @classmethod
    def create(cls) -> "ProcessingTimeTrigger":
        return cls()

    def on_processing_time(self, time: int, window: TimeWindow) -> str:
        return (TriggerResult.FIRE if time >= window.max_timestamp()
                else TriggerResult.CONTINUE)

    def fires_on_watermark(self) -> bool:
        return False


@dataclasses.dataclass(frozen=True)
class CountTrigger(Trigger):
    """FIRE every N elements per (key, window) (ref: triggers/CountTrigger
    .java). Device lowering checks the count lane after each step, so a
    fire can be up to one microbatch late relative to the reference's
    exact-Nth-element semantics — documented batching tradeoff."""

    max_count: int

    @classmethod
    def of(cls, n: int) -> "CountTrigger":
        return cls(n)

    def on_element(self, timestamp: int, window: TimeWindow, count: int) -> str:
        return TriggerResult.FIRE if count >= self.max_count else TriggerResult.CONTINUE


@dataclasses.dataclass(frozen=True)
class PurgingTrigger(Trigger):
    """Wraps a trigger, turning FIRE into FIRE_AND_PURGE
    (ref: triggers/PurgingTrigger.java)."""

    inner: Trigger

    @classmethod
    def of(cls, inner: Trigger) -> "PurgingTrigger":
        return cls(inner)

    def on_element(self, timestamp: int, window: TimeWindow, count: int) -> str:
        r = self.inner.on_element(timestamp, window, count)
        return TriggerResult.FIRE_AND_PURGE if r == TriggerResult.FIRE else r

    def on_event_time(self, time: int, window: TimeWindow) -> str:
        r = self.inner.on_event_time(time, window)
        return TriggerResult.FIRE_AND_PURGE if r == TriggerResult.FIRE else r

    def fires_on_watermark(self) -> bool:
        return self.inner.fires_on_watermark()
