"""Sinks — consume fired/transformed batches on the host.

ref: Sink API v2 (flink-core/.../api/connector/sink2/{Sink,SinkWriter,
Committer}.java). The exactly-once contract: a sink buffers writes per
checkpoint epoch and commits them only on ``notify_checkpoint_complete``
(the reference's two-phase-commit sink protocol, ref: streaming/runtime/
operators/sink/CommitterOperator.java); non-transactional sinks just
write through.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


def rows_of(batch: Dict[str, np.ndarray]) -> List[Dict[str, Any]]:
    """Explode a columnar batch into per-record dicts (the row view
    every collecting/printing sink shares)."""
    if not batch:
        return []
    n = len(next(iter(batch.values())))
    return [{k: v[i] for k, v in batch.items()} for i in range(n)]


class Sink:
    # Whether this sink understands op-typed changelog rows
    # (records.OP_FIELD): folding -U/-D retractions instead of appending
    # them as if they were inserts. Append-only sinks fed a retract
    # stream silently double-count — the analyzer rule
    # CHANGELOG_SINK_MISMATCH keys on this attribute.
    changelog_capable = False

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        raise NotImplementedError

    # -- exactly-once seam ------------------------------------------------
    def prepare_commit(self, checkpoint_id: int) -> None:
        """Stage everything written since the previous barrier under this
        checkpoint id (ref: SinkWriter.prepareCommit)."""

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Commit staged epochs <= checkpoint_id (ref: Committer.commit)."""

    def notify_checkpoint_abort(self, checkpoint_id: int) -> None:
        """The checkpoint covering this staged epoch failed before
        completing — the epoch's rows replay from source positions, so
        its staged transaction may be discarded (ref:
        CheckpointListener.notifyCheckpointAborted). Default no-op:
        non-transactional sinks have nothing staged."""

    def set_attempt_epoch(self, epoch: int) -> None:
        """The driver announces this attempt's fencing epoch before the
        run starts (``cluster.attempt``, the same counter that fences
        checkpoint storage as ``chk-<id>.e<epoch>``). Transactional
        sinks qualify in-progress artifacts with it so a deposed
        attempt restarting mid-commit can never clobber a successor's
        committed output. Default no-op."""

    # -- staged-transaction persistence seam ------------------------------
    # The reference's TwoPhaseCommitSinkFunction keeps pending transactions
    # IN STATE and re-commits them on restore — a crash between the
    # checkpoint write and the commit round must not lose the epoch.
    def snapshot_staged(self) -> Optional[Any]:
        """Staged-but-uncommitted transactions to persist in the
        checkpoint; None = sink is not transactional."""
        return None

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        """Re-commit staged epochs <= checkpoint_id (the checkpoint's
        completion proves they must become visible); abort the rest."""

    def close(self) -> None:
        pass


def sink_is_transactional(sink: "Sink") -> bool:
    """Whether a sink instance participates in exactly-once 2PC — it
    overrides the staging seam (``prepare_commit``) or the persistence
    seam (``snapshot_staged``). Single-sourced here because TWO analyzer
    rules key on it (NON_TRANSACTIONAL_SINK and its log-chain
    escalation NON_TXN_SINK_IN_CHAIN) and must never disagree about
    what "transactional" means."""
    cls = type(sink)
    return (cls.prepare_commit is not Sink.prepare_commit
            or cls.snapshot_staged is not Sink.snapshot_staged)


class TwoPhaseCommitSink(Sink):
    """Generalized pre-commit/commit transactional sink protocol (ref:
    TwoPhaseCommitSinkFunction + the FLIP-143 unified Sink's
    writer/committer split, generalized from SURVEY §3.9's
    rename-on-commit). The base owns the TRANSACTION bookkeeping; a
    subclass owns the in-memory buffer and the durable medium:

    - ``write()`` buffers rows in memory (subclass-owned shape);
    - ``prepare_commit(cid)`` (checkpoint barrier) calls
      ``stage_transaction(cid)``: the subclass makes everything
      buffered DURABLE under transaction ``cid`` — data plus a fsynced
      pre-commit marker — without making any of it visible;
    - ``notify_checkpoint_complete(cid)`` (checkpoint completion)
      commits every staged transaction with id <= cid in id order —
      ``commit_transaction`` is the atomic visibility point and must be
      idempotent (a restore replays commits);
    - ``notify_checkpoint_abort(cid)`` / ``abort_uncommitted()`` roll
      staged transactions back durably (their rows replay from source
      positions);
    - staged transactions additionally ride INSIDE the checkpoint
      payload (``snapshot_transaction``), so a crash that lands between
      the checkpoint's manifest write and the commit round — or a
      cleanup that deleted the staged artifacts — can always
      ``rebuild_transaction`` and re-commit on restore.
    """

    # -- subclass contract (durable-medium operations) --------------------
    def drop_pending(self) -> None:
        """Clear the in-memory (never-staged) buffer."""
        raise NotImplementedError

    def stage_transaction(self, cid: int) -> bool:
        """Durably stage everything buffered since the last barrier as
        transaction ``cid`` (data + pre-commit marker, fsynced) and
        clear the buffer. Return False when nothing was buffered (no
        empty transactions)."""
        raise NotImplementedError

    def staged_transaction_ids(self) -> List[int]:
        """Ids of transactions staged on the durable medium but not yet
        committed (sorted ascending)."""
        raise NotImplementedError

    def commit_transaction(self, cid: int) -> None:
        """Atomically publish transaction ``cid``. MUST be idempotent —
        restore replays commits — and a no-op for unknown ids (an empty
        epoch staged nothing)."""
        raise NotImplementedError

    def abort_transaction(self, cid: int) -> None:
        """Durably discard staged transaction ``cid`` (idempotent)."""
        raise NotImplementedError

    def snapshot_transaction(self, cid: int) -> Any:
        """Payload from which ``rebuild_transaction`` can reconstruct
        the staged transaction — rides inside the checkpoint."""
        raise NotImplementedError

    def rebuild_transaction(self, cid: int, payload: Any) -> None:
        """Re-create staged transaction ``cid`` from its checkpoint
        payload if it is no longer on the durable medium (idempotent;
        a commit_transaction call follows)."""
        raise NotImplementedError

    def cleanup_unreferenced(self) -> None:
        """Optional hook: sweep torn half-staged debris no marker
        references (a crash mid-stage). Default no-op."""

    # -- the protocol (driver-facing, final) ------------------------------
    def _live_staged(self) -> set:
        """Cids THIS instance staged rows for (stage_transaction
        returned True) and has not yet committed/aborted. The commit
        round walks the union of this set and the on-disk staged ids,
        so a staged transaction whose durable marker VANISHED before
        commit still reaches commit_transaction — where the medium can
        fail loudly instead of the epoch silently disappearing from
        the staged listing (lazy init: subclasses own __init__)."""
        s = getattr(self, "_live_staged_ids", None)
        if s is None:
            s = self._live_staged_ids = set()
        return s

    def prepare_commit(self, checkpoint_id: int) -> None:
        if self.stage_transaction(checkpoint_id):
            self._live_staged().add(int(checkpoint_id))

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        live = self._live_staged()
        for cid in sorted(set(self.staged_transaction_ids()) | live):
            if cid <= checkpoint_id:
                self.commit_transaction(cid)
                live.discard(cid)

    def notify_checkpoint_abort(self, checkpoint_id: int) -> None:
        if checkpoint_id in self.staged_transaction_ids():
            self.abort_transaction(checkpoint_id)
        self._live_staged().discard(int(checkpoint_id))

    def snapshot_staged(self) -> Any:
        return {"txn": {str(cid): self.snapshot_transaction(cid)
                        for cid in self.staged_transaction_ids()}}

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        self.drop_pending()
        self._live_staged().clear()  # staged knowledge now comes from
        # the checkpoint payload, not this instance's write history
        txns = {int(c): p
                for c, p in (staged or {}).get("txn", {}).items()}
        for cid in sorted(txns):
            if cid <= checkpoint_id:
                # the completed checkpoint proves this epoch must become
                # visible even though the commit round never ran; if an
                # abort deleted the staged artifacts in the meantime,
                # rebuild them from the payload first
                self.rebuild_transaction(cid, txns[cid])
                self.commit_transaction(cid)
        # anything still staged is either uncovered (replays from source
        # positions) or a dead attempt's leftovers — roll it back
        for cid in self.staged_transaction_ids():
            self.abort_transaction(cid)
        self.cleanup_unreferenced()

    def abort_uncommitted(self) -> None:
        self.drop_pending()
        for cid in self.staged_transaction_ids():
            self.abort_transaction(cid)
        self._live_staged().clear()
        self.cleanup_unreferenced()


@dataclasses.dataclass
class CollectSink(Sink):
    """Gather results in memory (ref: DataStream.executeAndCollect)."""

    rows: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self.rows.extend(rows_of(batch))

    def batches(self) -> List[Dict[str, np.ndarray]]:
        return self.rows


@dataclasses.dataclass
class PrintSink(Sink):
    """ref: DataStream.print / PrintSinkFunction."""

    prefix: str = ""
    limit: Optional[int] = None
    _printed: int = 0

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        for row in rows_of(batch):
            if self.limit is not None and self._printed >= self.limit:
                return
            print(f"{self.prefix}{row}")
            self._printed += 1


@dataclasses.dataclass
class FnSink(Sink):
    """Adapter for a plain callable(batch_dict). The callable receives
    raw batches — op columns included — so it is trusted to handle
    changelog streams (it sees records.OP_FIELD and can fold)."""

    fn: Callable[[Dict[str, np.ndarray]], None]
    changelog_capable = True

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self.fn(batch)


@dataclasses.dataclass
class UpsertSink(Sink):
    """Materialize an UPSERT stream as latest-row-by-key (ref: the
    upsert-kafka/table sink contract — each arriving row replaces the
    previous row with the same key tuple). Op-typed changelog rows
    (records.OP_FIELD) fold: +I/+U replace the key's row, -U/-D delete
    it (deleting on -U is safe AND necessary: in a full changelog the
    superseding +U follows in order and re-inserts, while after a
    HAVING-style filter a surviving -U with no +U partner IS the key
    leaving the view). ``view()`` returns the current table."""

    key_fields: Tuple[str, ...] = ("key",)
    state: Dict[Any, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    changelog_capable = True

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        from flink_tpu.records import OP_DELETE, OP_FIELD, OP_UPDATE_BEFORE

        has_op = OP_FIELD in batch
        for row in rows_of(batch):
            op = int(row.pop(OP_FIELD)) if has_op else None
            k = tuple(row[f] for f in self.key_fields)
            if op in (OP_UPDATE_BEFORE, OP_DELETE):
                self.state.pop(k, None)
            else:
                self.state[k] = row

    def view(self) -> List[Dict[str, Any]]:
        return list(self.state.values())


@dataclasses.dataclass
class RetractSink(Sink):
    """Exactly-once changelog materialization: op-typed rows fold into a
    keyed table, and the table advances only when an epoch's checkpoint
    completes (ref: the table-runtime retract sink contract riding the
    TwoPhaseCommitSinkFunction protocol). -U/-D remove the key's row;
    +I/+U (re)place it; rows without an op column are upserts. Arrival
    order within an epoch is preserved, so a -U/+U pair nets to the
    update. Uncommitted epochs are discarded on restore — after
    recovery the table equals exactly what the restored checkpoint
    proved, then re-evolves from replayed input."""

    key_fields: Tuple[str, ...] = ("key",)
    table: Dict[Any, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    changelog_capable = True

    def __post_init__(self) -> None:
        self._pending: List[Dict[str, Any]] = []
        self._staged: Dict[int, List[Dict[str, Any]]] = {}
        self._last_committed = 0

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._pending.extend(rows_of(batch))

    def _apply(self, rows: List[Dict[str, Any]]) -> None:
        from flink_tpu.records import OP_DELETE, OP_FIELD, OP_UPDATE_BEFORE

        for row in rows:
            row = dict(row)
            op = int(row.pop(OP_FIELD)) if OP_FIELD in row else None
            k = tuple(row[f] for f in self.key_fields)
            if op in (OP_UPDATE_BEFORE, OP_DELETE):
                self.table.pop(k, None)
            else:
                self.table[k] = row

    def view(self) -> List[Dict[str, Any]]:
        """The committed table, ordered by key tuple (deterministic
        across runs/restores — insertion order is an epoch artifact)."""
        return [self.table[k] for k in sorted(self.table)]

    # -- exactly-once protocol (TransactionalCollectSink's shape) ---------
    def prepare_commit(self, checkpoint_id: int) -> None:
        self._staged[checkpoint_id] = self._pending
        self._pending = []

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for cid in sorted([c for c in self._staged if c <= checkpoint_id]):
            self._apply(self._staged.pop(cid))
            self._last_committed = max(self._last_committed, cid)

    def snapshot_staged(self) -> Any:
        # called AFTER prepare_commit(cid): the in-flight checkpoint's
        # own epoch rides inside its payload
        return {cid: list(rows) for cid, rows in self._staged.items()}

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        self._pending = []
        self._staged = {}
        for cid in sorted(staged):
            if cid <= checkpoint_id:
                # checkpoint N completing proves epoch N folds in;
                # the re-commit guard keeps the replay idempotent when
                # the same instance survives the restore
                if cid > self._last_committed:
                    self._apply(staged[cid])
                    self._last_committed = cid
            # epochs staged after the restored checkpoint replay from
            # source positions — drop them

    def abort_uncommitted(self) -> None:
        self._staged.clear()
        self._pending = []


@dataclasses.dataclass
class TransactionalCollectSink(Sink):
    """Exactly-once collect: rows become visible only when their epoch's
    checkpoint completes; uncommitted epochs are discarded on restore
    (the TwoPhaseCommitSinkFunction contract, ref: streaming/api/
    functions/sink/TwoPhaseCommitSinkFunction.java)."""

    committed: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        self._pending: List[Dict[str, Any]] = []
        self._staged: Dict[int, List[Dict[str, Any]]] = {}
        self._last_committed = 0

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._pending.extend(rows_of(batch))

    def prepare_commit(self, checkpoint_id: int) -> None:
        self._staged[checkpoint_id] = self._pending
        self._pending = []

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for cid in sorted([c for c in self._staged if c <= checkpoint_id]):
            self.committed.extend(self._staged.pop(cid))
            self._last_committed = max(self._last_committed, cid)

    def snapshot_staged(self) -> Any:
        # called AFTER prepare_commit(cid) staged the current epoch, so the
        # epoch the in-flight checkpoint covers rides inside its own payload
        return {cid: list(rows) for cid, rows in self._staged.items()}

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        self._pending = []
        self._staged = {}
        for cid in sorted(staged):
            if cid <= checkpoint_id:
                # checkpoint N completing proves epoch N must be visible;
                # re-commit idempotently (a crash may have landed anywhere
                # between the manifest write and the commit round)
                if cid > self._last_committed:
                    self.committed.extend(staged[cid])
                    self._last_committed = cid
            # epochs staged after the restored checkpoint replay from
            # source positions — drop them

    def abort_uncommitted(self) -> None:
        """Fresh-start path (no checkpoint found): drop anything a prior
        attempt staged or buffered on this reused sink instance."""
        self._staged.clear()
        self._pending = []


class FileTransactionalSink(Sink):
    """Exactly-once FILE sink: epochs stage as ``staged/epoch-N.jsonl``
    at prepare time and become visible via atomic rename into
    ``committed/`` when their checkpoint completes — the classic
    write-ahead / rename-on-commit pattern (ref: FileSink +
    TwoPhaseCommitSinkFunction, flink-connectors/flink-connector-files).
    Because the staging ground is the filesystem, the transaction state
    survives PROCESS DEATH: a new attempt in a new process restores or
    aborts the crashed attempt's epochs from disk."""

    def __init__(self, directory: str) -> None:
        from flink_tpu.fs import get_filesystem

        self.dir = directory
        self._fs = get_filesystem(directory)
        self._staged_dir = os.path.join(directory, "staged")
        self._committed_dir = os.path.join(directory, "committed")
        self._fs.mkdirs(self._staged_dir)
        self._fs.mkdirs(self._committed_dir)
        self._pending: List[Dict[str, Any]] = []

    @staticmethod
    def _jsonable(v: Any) -> Any:
        a = np.asarray(v)
        return int(v) if np.issubdtype(a.dtype, np.integer) else (
            float(v) if np.issubdtype(a.dtype, np.floating) else str(v))

    def _staged_path(self, cid: int) -> str:
        return os.path.join(self._staged_dir, f"epoch-{cid:010d}.jsonl")

    def _committed_path(self, cid: int) -> str:
        return os.path.join(self._committed_dir, f"epoch-{cid:010d}.jsonl")

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._pending.extend(
            {k: self._jsonable(v) for k, v in row.items()}
            for row in rows_of(batch))

    def prepare_commit(self, checkpoint_id: int) -> None:
        from flink_tpu.fs import write_atomic

        payload = "".join(
            json.dumps(row) + "\n" for row in self._pending)
        write_atomic(self._fs, self._staged_path(checkpoint_id),
                     payload.encode("utf-8"))
        self._pending = []

    def _commit_epoch(self, cid: int) -> None:
        sp, cp = self._staged_path(cid), self._committed_path(cid)
        if self._fs.exists(cp):
            # already committed (restore replays the commit idempotently)
            if self._fs.exists(sp):
                self._fs.delete(sp)
        elif self._fs.exists(sp):
            self._fs.rename(sp, cp)  # atomic: the commit point

    def _staged_cids(self) -> List[int]:
        return sorted(
            int(f[len("epoch-"):-len(".jsonl")])
            for f in self._fs.listdir(self._staged_dir)
            if f.startswith("epoch-") and f.endswith(".jsonl"))

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for cid in self._staged_cids():
            if cid <= checkpoint_id:
                self._commit_epoch(cid)

    def snapshot_staged(self) -> Any:
        # staged ROWS ride inside the checkpoint payload, not just their
        # epoch ids: a cleanup between the manifest write and the commit
        # round may delete the staged FILES (abort_uncommitted on a
        # failed attempt), and the restore must then be able to
        # reconstruct the covered epoch from the payload — otherwise its
        # rows are gone (sources replay only post-checkpoint)
        epochs = {}
        for cid in self._staged_cids():
            with self._fs.open_read(self._staged_path(cid)) as f:
                raw = f.read()
            if isinstance(raw, bytes):
                raw = raw.decode("utf-8")
            epochs[str(cid)] = [
                json.loads(line) for line in raw.splitlines()
                if line.strip()]
        return {"epochs": epochs}

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        self._pending = []
        epochs = {int(c): rows for c, rows in staged.get("epochs", {}).items()}
        for cid, rows in sorted(epochs.items()):
            if cid > checkpoint_id:
                continue
            # the completed checkpoint proves this epoch must be
            # visible even though the commit round never ran; if the
            # staged file was deleted in the meantime, rebuild it from
            # the payload before committing
            if not self._fs.exists(self._committed_path(cid)):
                if not self._fs.exists(self._staged_path(cid)):
                    self._pending = rows
                    self.prepare_commit(cid)
                self._commit_epoch(cid)
        # anything still staged on disk is either uncovered (replays
        # from source positions) or a later attempt's leftovers — drop
        for cid in self._staged_cids():
            self._fs.delete(self._staged_path(cid))

    def abort_uncommitted(self) -> None:
        self._pending = []
        for cid in self._staged_cids():
            self._fs.delete(self._staged_path(cid))

    @classmethod
    def committed_rows(cls, directory: str) -> List[Dict[str, Any]]:
        """Read back every committed row (commit order) — the consumer
        view of the sink's output."""
        cdir = os.path.join(directory, "committed")
        rows: List[Dict[str, Any]] = []
        if not os.path.isdir(cdir):
            return rows
        for f in sorted(os.listdir(cdir)):
            if f.startswith("epoch-") and f.endswith(".jsonl"):
                with open(os.path.join(cdir, f)) as fh:
                    for line in fh:
                        if line.strip():
                            rows.append(json.loads(line))
        return rows
