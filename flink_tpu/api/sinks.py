"""Sinks — consume fired/transformed batches on the host.

ref: Sink API v2 (flink-core/.../api/connector/sink2/{Sink,SinkWriter,
Committer}.java). The exactly-once contract: a sink buffers writes per
checkpoint epoch and commits them only on ``notify_checkpoint_complete``
(the reference's two-phase-commit sink protocol, ref: streaming/runtime/
operators/sink/CommitterOperator.java); non-transactional sinks just
write through.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Sink:
    def write(self, batch: Dict[str, np.ndarray]) -> None:
        raise NotImplementedError

    # -- exactly-once seam ------------------------------------------------
    def prepare_commit(self, checkpoint_id: int) -> None:
        """Stage everything written since the previous barrier under this
        checkpoint id (ref: SinkWriter.prepareCommit)."""

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Commit staged epochs <= checkpoint_id (ref: Committer.commit)."""

    # -- staged-transaction persistence seam ------------------------------
    # The reference's TwoPhaseCommitSinkFunction keeps pending transactions
    # IN STATE and re-commits them on restore — a crash between the
    # checkpoint write and the commit round must not lose the epoch.
    def snapshot_staged(self) -> Optional[Any]:
        """Staged-but-uncommitted transactions to persist in the
        checkpoint; None = sink is not transactional."""
        return None

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        """Re-commit staged epochs <= checkpoint_id (the checkpoint's
        completion proves they must become visible); abort the rest."""

    def close(self) -> None:
        pass


@dataclasses.dataclass
class CollectSink(Sink):
    """Gather results in memory (ref: DataStream.executeAndCollect)."""

    rows: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        if not batch:
            return
        n = len(next(iter(batch.values())))
        for i in range(n):
            self.rows.append({k: v[i] for k, v in batch.items()})

    def batches(self) -> List[Dict[str, np.ndarray]]:
        return self.rows


@dataclasses.dataclass
class PrintSink(Sink):
    """ref: DataStream.print / PrintSinkFunction."""

    prefix: str = ""
    limit: Optional[int] = None
    _printed: int = 0

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        if not batch:
            return
        n = len(next(iter(batch.values())))
        for i in range(n):
            if self.limit is not None and self._printed >= self.limit:
                return
            row = {k: v[i] for k, v in batch.items()}
            print(f"{self.prefix}{row}")
            self._printed += 1


@dataclasses.dataclass
class FnSink(Sink):
    """Adapter for a plain callable(batch_dict)."""

    fn: Callable[[Dict[str, np.ndarray]], None]

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self.fn(batch)


@dataclasses.dataclass
class TransactionalCollectSink(Sink):
    """Exactly-once collect: rows become visible only when their epoch's
    checkpoint completes; uncommitted epochs are discarded on restore
    (the TwoPhaseCommitSinkFunction contract, ref: streaming/api/
    functions/sink/TwoPhaseCommitSinkFunction.java)."""

    committed: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        self._pending: List[Dict[str, Any]] = []
        self._staged: Dict[int, List[Dict[str, Any]]] = {}
        self._last_committed = 0

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        if not batch:
            return
        n = len(next(iter(batch.values())))
        for i in range(n):
            self._pending.append({k: v[i] for k, v in batch.items()})

    def prepare_commit(self, checkpoint_id: int) -> None:
        self._staged[checkpoint_id] = self._pending
        self._pending = []

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for cid in sorted([c for c in self._staged if c <= checkpoint_id]):
            self.committed.extend(self._staged.pop(cid))
            self._last_committed = max(self._last_committed, cid)

    def snapshot_staged(self) -> Any:
        # called AFTER prepare_commit(cid) staged the current epoch, so the
        # epoch the in-flight checkpoint covers rides inside its own payload
        return {cid: list(rows) for cid, rows in self._staged.items()}

    def restore_staged(self, staged: Any, checkpoint_id: int) -> None:
        self._pending = []
        self._staged = {}
        for cid in sorted(staged):
            if cid <= checkpoint_id:
                # checkpoint N completing proves epoch N must be visible;
                # re-commit idempotently (a crash may have landed anywhere
                # between the manifest write and the commit round)
                if cid > self._last_committed:
                    self.committed.extend(staged[cid])
                    self._last_committed = cid
            # epochs staged after the restored checkpoint replay from
            # source positions — drop them

    def abort_uncommitted(self) -> None:
        """Fresh-start path (no checkpoint found): drop anything a prior
        attempt staged or buffered on this reused sink instance."""
        self._staged.clear()
        self._pending = []
