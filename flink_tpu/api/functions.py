"""User function interfaces.

ref: flink-core/.../api/common/functions/{MapFunction,FilterFunction,
FlatMapFunction,ReduceFunction,AggregateFunction}.java and
streaming/api/functions/{ProcessFunction,windowing/ProcessWindowFunction}.

TPU-first redesign: user functions are **jax-traceable batch functions**
over struct-of-arrays record data — they get traced into the stage's
compiled step function exactly once (the analogue of operator chaining +
codegen; ref: StreamingJobGraphGenerator.isChainable fuses same-thread
operators, here XLA fuses the traced ops). Scalar-style functions are
supported via implicit vmap for convenience, but batch style is the
native path.

A "value" is a dict field→(B,) array (a RecordBatch's data view).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


class MapFunction:
    """1→1 transform (ref: MapFunction.java). Override ``map_batch`` for
    the native vectorized path, or ``map`` for per-record (vmapped)."""

    def map(self, value: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def map_batch(self, values: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return jax.vmap(self.map)(values)


class FilterFunction:
    """Keep rows where the predicate holds (ref: FilterFunction.java).
    Lowered to a validity-mask AND — rows are never compacted on device
    (static shapes); downstream ops skip invalid rows."""

    def filter(self, value: Dict[str, jax.Array]) -> jax.Array:
        raise NotImplementedError

    def filter_batch(self, values: Dict[str, jax.Array]) -> jax.Array:
        return jax.vmap(self.filter)(values)


class FlatMapFunction:
    """1→[0..k] transform with a STATIC max fan-out (ref: FlatMapFunction
    .java). Dynamic output counts can't exist under jit; emit up to
    ``max_fanout`` rows per input with a validity mask."""

    max_fanout: int = 1

    def flat_map_batch(
        self, values: Dict[str, jax.Array], valid: jax.Array
    ) -> tuple[Dict[str, jax.Array], jax.Array]:
        """Return (data with leading dim B*max_fanout, valid mask)."""
        raise NotImplementedError


class ReduceFunction:
    """Commutative+associative combine of two values of the same type
    (ref: ReduceFunction.java). Must be expressible as elementwise
    sum/min/max lanes for the dense pane path (SURVEY §8 lane design);
    anything else is rejected at lowering time with a pointer to
    composing ops.aggregates lanes (no silent wrong answers)."""

    def reduce(self, a: Dict[str, jax.Array], b: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        raise NotImplementedError


class AggregateFunction:
    """Incremental aggregation ACC/IN/OUT (ref: AggregateFunction.java —
    createAccumulator/add/merge/getResult). The accumulator is a pytree
    of scalars; ``add`` and ``merge`` must be jax-traceable. The window
    operator lowers instances whose merge is a per-leaf sum/min/max to
    the dense lane layout automatically (ops/aggregates.lower_aggregate
    probes the merge); anything else raises at lowering time with a
    pointer to composing ops.aggregates lanes — loud, never wrong."""

    def create_accumulator(self) -> Any:
        raise NotImplementedError

    def add(self, value: Dict[str, jax.Array], acc: Any) -> Any:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def get_result(self, acc: Any) -> Any:
        raise NotImplementedError


class ProcessWindowFunction:
    """Post-aggregation per-window hook with window metadata (ref:
    streaming/api/functions/windowing/ProcessWindowFunction.java, applied
    via InternalAggregateProcessWindowFunction). Receives the fired
    (key, window, result) batch; runs on device, vectorized."""

    def process_batch(
        self,
        keys: jax.Array,
        window_starts: jax.Array,
        window_ends: jax.Array,
        results: Any,
        valid: jax.Array,
    ) -> Any:
        return results


class KeyedProcessFunction:
    """General keyed processing with state and timers (ref: streaming/
    api/functions/KeyedProcessFunction.java via KeyedProcessOperator).

    Native authoring style is per-BATCH: override ``process_batch(ctx)``
    and ``on_timer(ctx)`` — ``ctx`` (ops/process.ProcessContext) carries
    the microbatch as struct-of-arrays (``ctx.keys/slots/timestamps/
    data``), columnar state handles (``ctx.value_state/list_state/
    map_state``), vectorized timer registration, and ``ctx.emit``.

    The reference's element-at-a-time style is available by overriding
    ``process_element(key, ts, row, ctx, slot)`` instead — the default
    ``process_batch`` loops it over the batch (host-loop speed; use it
    only when the logic is truly sequential per record)."""

    def process_batch(self, ctx) -> None:
        import numpy as np

        for i in range(len(ctx.keys)):
            row = {k: v[i] for k, v in ctx.data.items()}
            self.process_element(int(ctx.keys[i]), int(ctx.timestamps[i]),
                                 row, ctx, int(ctx.slots[i]))

    def process_element(self, key: int, ts: int, row: Dict[str, Any],
                        ctx, slot: int) -> None:
        raise NotImplementedError(
            "override process_batch (vectorized) or process_element")

    def on_timer(self, ctx) -> None:
        """Called once per watermark advance with ALL due timers as
        arrays (ctx.keys/slots/timestamps)."""


# -- convenience lambdas -----------------------------------------------------

@dataclasses.dataclass
class LambdaMap(MapFunction):
    fn: Callable[[Dict[str, jax.Array]], Dict[str, jax.Array]]
    batch: bool = True

    def map(self, value):
        return self.fn(value)

    def map_batch(self, values):
        if self.batch:
            return self.fn(values)
        return jax.vmap(self.fn)(values)


@dataclasses.dataclass
class LambdaFilter(FilterFunction):
    fn: Callable[[Dict[str, jax.Array]], jax.Array]
    batch: bool = True

    def filter(self, value):
        return self.fn(value)

    def filter_batch(self, values):
        if self.batch:
            return self.fn(values)
        return jax.vmap(self.fn)(values)
