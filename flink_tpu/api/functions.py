"""User function interfaces.

ref: flink-core/.../api/common/functions/{MapFunction,FilterFunction,
FlatMapFunction,ReduceFunction,AggregateFunction}.java and
streaming/api/functions/{ProcessFunction,windowing/ProcessWindowFunction}.

TPU-first redesign: user functions are **jax-traceable batch functions**
over struct-of-arrays record data — they get traced into the stage's
compiled step function exactly once (the analogue of operator chaining +
codegen; ref: StreamingJobGraphGenerator.isChainable fuses same-thread
operators, here XLA fuses the traced ops). Scalar-style functions are
supported via implicit vmap for convenience, but batch style is the
native path.

A "value" is a dict field→(B,) array (a RecordBatch's data view).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


class MapFunction:
    """1→1 transform (ref: MapFunction.java). Override ``map_batch`` for
    the native vectorized path, or ``map`` for per-record (vmapped)."""

    def map(self, value: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def map_batch(self, values: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return jax.vmap(self.map)(values)


class FilterFunction:
    """Keep rows where the predicate holds (ref: FilterFunction.java).
    Lowered to a validity-mask AND — rows are never compacted on device
    (static shapes); downstream ops skip invalid rows."""

    def filter(self, value: Dict[str, jax.Array]) -> jax.Array:
        raise NotImplementedError

    def filter_batch(self, values: Dict[str, jax.Array]) -> jax.Array:
        return jax.vmap(self.filter)(values)


class FlatMapFunction:
    """1→[0..k] transform with a STATIC max fan-out (ref: FlatMapFunction
    .java). Dynamic output counts can't exist under jit; emit up to
    ``max_fanout`` rows per input with a validity mask."""

    max_fanout: int = 1

    def flat_map_batch(
        self, values: Dict[str, jax.Array], valid: jax.Array
    ) -> tuple[Dict[str, jax.Array], jax.Array]:
        """Return (data with leading dim B*max_fanout, valid mask)."""
        raise NotImplementedError


class ReduceFunction:
    """Commutative+associative combine of two values of the same type
    (ref: ReduceFunction.java). Must be expressible as elementwise
    sum/min/max lanes for the dense pane path (SURVEY §8 lane design);
    arbitrary reduces go through the sort+scan fallback."""

    def reduce(self, a: Dict[str, jax.Array], b: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        raise NotImplementedError


class AggregateFunction:
    """Incremental aggregation ACC/IN/OUT (ref: AggregateFunction.java —
    createAccumulator/add/merge/getResult). The accumulator is a pytree
    of scalars; ``add`` and ``merge`` must be jax-traceable. The window
    operator lowers instances whose merge is a per-leaf sum/min/max to
    the dense lane layout automatically (ops/aggregates.lower_aggregate);
    others use the generic sort+segment-scan path."""

    def create_accumulator(self) -> Any:
        raise NotImplementedError

    def add(self, value: Dict[str, jax.Array], acc: Any) -> Any:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def get_result(self, acc: Any) -> Any:
        raise NotImplementedError


class ProcessWindowFunction:
    """Post-aggregation per-window hook with window metadata (ref:
    streaming/api/functions/windowing/ProcessWindowFunction.java, applied
    via InternalAggregateProcessWindowFunction). Receives the fired
    (key, window, result) batch; runs on device, vectorized."""

    def process_batch(
        self,
        keys: jax.Array,
        window_starts: jax.Array,
        window_ends: jax.Array,
        results: Any,
        valid: jax.Array,
    ) -> Any:
        return results


# -- convenience lambdas -----------------------------------------------------

@dataclasses.dataclass
class LambdaMap(MapFunction):
    fn: Callable[[Dict[str, jax.Array]], Dict[str, jax.Array]]
    batch: bool = True

    def map(self, value):
        return self.fn(value)

    def map_batch(self, values):
        if self.batch:
            return self.fn(values)
        return jax.vmap(self.fn)(values)


@dataclasses.dataclass
class LambdaFilter(FilterFunction):
    fn: Callable[[Dict[str, jax.Array]], jax.Array]
    batch: bool = True

    def filter(self, value):
        return self.fn(value)

    def filter_batch(self, values):
        if self.batch:
            return self.fn(values)
        return jax.vmap(self.fn)(values)
