"""StreamExecutionEnvironment — program entry and execution.

ref: streaming/api/environment/StreamExecutionEnvironment.java
(getExecutionEnvironment, fromCollection/fromSource, execute →
StreamGraphGenerator → JobGraph → submission).

TPU-first: ``execute()`` lowers the transformation DAG to fused stages
(graph/compiler.py) and runs them on the local driver (runtime/driver.py)
over the configured device mesh — the LocalExecutor/MiniCluster path.
Remote submission to a coordinator process reuses the same lowered plan
(runtime/coordinator.py).
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from flink_tpu.api.datastream import DataStream
from flink_tpu.api.sources import CollectionSource, Source
from flink_tpu.config import Configuration
from flink_tpu.graph.transformations import SourceTransformation, Transformation
from flink_tpu.time.watermarks import WatermarkStrategy


class StreamExecutionEnvironment:
    def __init__(self, config: Optional[Configuration] = None):
        self.config = config or Configuration()
        self._transforms: List[Transformation] = []
        self._watermark_strategy = WatermarkStrategy.for_monotonous_timestamps()
        # plugin loading happens at env creation — the PluginManager
        # point where filesystem schemes must be ready (ref: FileSystem
        # .initialize at cluster entrypoint)
        from flink_tpu.config import CoreOptions

        mods = self.config.get(CoreOptions.PLUGINS)
        if mods:
            from flink_tpu.fs import load_plugins

            load_plugins(mods.split(","))

    @classmethod
    def get_execution_environment(
        cls, config: Optional[Configuration] = None
    ) -> "StreamExecutionEnvironment":
        return cls(config)

    # -- sources ---------------------------------------------------------
    def from_source(
        self,
        source: Source,
        watermark_strategy: Optional[WatermarkStrategy] = None,
        name: str = "source",
    ) -> DataStream:
        t = SourceTransformation(name, (), source=source,
                                 watermark_strategy=watermark_strategy)
        self._register(t)
        return DataStream(self, t)

    def from_collection(
        self,
        data: Mapping[str, np.ndarray],
        timestamps: np.ndarray,
        batch_size: Optional[int] = None,
        name: str = "collection",
    ) -> DataStream:
        from flink_tpu.config import PipelineOptions

        bs = batch_size or self.config.get(PipelineOptions.MICROBATCH_SIZE)
        return self.from_source(
            CollectionSource(dict(data), np.asarray(timestamps, np.int64), bs),
            name=name)

    def _register(self, t: Transformation) -> None:
        self._transforms.append(t)

    def set_runtime_mode(self, mode: str) -> "StreamExecutionEnvironment":
        """'streaming' | 'batch' (ref: StreamExecutionEnvironment
        .setRuntimeMode / execution.runtime-mode). Batch = bounded
        execution: every source must report bounded=True; stages run
        in topological waves over blocking columnar exchanges and
        windows fire once at end-of-input (graph/compiler.py +
        runtime/driver.py _run_batch). Validated at compile time."""
        from flink_tpu.config import ExecutionOptions

        self.config.set(ExecutionOptions.RUNTIME_MODE, mode)
        return self

    # -- execution -------------------------------------------------------
    def execute(self, job_name: str = "job", cancel=None,
                savepoint_request=None, transforms=None) -> "JobResult":
        """Lower and run to completion (bounded) or until cancelled
        (ref: execute → LocalExecutor → MiniCluster.submitJob). With
        ``cluster.mesh-devices`` set, keyed state is sharded over the
        device mesh and the driver runs the distributed step. ``cancel``
        is an optional threading.Event: setting it aborts the job at the
        next batch boundary with JobCancelledError. ``transforms``
        restricts the run to a subset of the registered graph (the Table
        API executes one query's lineage, not every pipeline ever built
        on this environment)."""
        from flink_tpu.graph.compiler import compile_job
        from flink_tpu.runtime.driver import Driver

        plan = compile_job(
            self._transforms if transforms is None else transforms,
            self.config, self._watermark_strategy)
        driver = Driver(plan, self.config, mesh_plan=self.build_mesh_plan())
        # live-metrics seam: the cluster runner reads this driver's
        # counters for heartbeat-carried job metrics (web UI gauges)
        self._driver = driver
        return driver.run(job_name, cancel=cancel,
                          savepoint_request=savepoint_request)

    def build_mesh_plan(self):
        """MeshPlan from ``cluster.mesh-devices`` (None = local
        single-device execution — the default)."""
        from flink_tpu.config import ClusterOptions, StateOptions

        spec = str(self.config.get(ClusterOptions.MESH_DEVICES)).strip()
        if not spec:
            return None
        import jax

        from flink_tpu.parallel.mesh import make_mesh_plan

        devices = jax.devices()
        if spec != "all":
            n = int(spec)
            if n < 1:
                raise ValueError(
                    f"cluster.mesh-devices must be 'all' or a positive "
                    f"integer, got {spec!r}")
            if n > len(devices):
                raise ValueError(
                    f"cluster.mesh-devices={n} but only {len(devices)} "
                    "devices are visible")
            devices = devices[:n]
        if len(devices) == 1:
            return None  # a 1-device mesh is just local execution
        num_shards = self.config.get(StateOptions.NUM_KEY_SHARDS)
        nproc = int(self.config.get(ClusterOptions.NUM_PROCESSES))
        if nproc > 1:
            # cross-host: the HYBRID topology (SNIPPETS.md [1] — DCN
            # outer axis, ICI inner). This process's local mesh covers
            # only its contiguous shard span; records arrive pre-routed
            # through the DCN exchange, so every in-step collective
            # names the inner axis only and keyBy shuffle bytes stay
            # intra-slice (the key directory keeps the global space)
            from flink_tpu.parallel.mesh import make_hybrid_mesh_plan

            return make_hybrid_mesh_plan(
                num_shards,
                self.config.get(StateOptions.SLOTS_PER_SHARD),
                nproc,
                int(self.config.get(ClusterOptions.PROCESS_ID)),
                devices)
        return make_mesh_plan(
            num_shards,
            self.config.get(StateOptions.SLOTS_PER_SHARD),
            devices)

    def compile_plan(self, strict: bool = True):
        """Lowered execution plan without running (inspection/tests —
        the getExecutionPlan analogue). ``strict=False`` lowers plans
        strict compilation rejects, so the analyzer can report the
        violations as findings (`python -m flink_tpu analyze`)."""
        from flink_tpu.graph.compiler import compile_job

        return compile_job(self._transforms, self.config,
                           self._watermark_strategy, strict=strict)

    def analyze(self):
        """Run compile-time plan analysis over this environment's
        pipeline + config without executing (the `flink_tpu analyze`
        surface; the driver runs the same rules at submit under
        ``analysis.fail-on``). Returns the findings list."""
        from flink_tpu.analysis import analyze

        return analyze(self.compile_plan(strict=False), self.config)


class JobResult:
    """ref: api/common/JobExecutionResult.java"""

    def __init__(self, job_name: str, metrics: Dict[str, Any]):
        self.job_name = job_name
        self.metrics = metrics

    def __repr__(self) -> str:
        return f"JobResult({self.job_name}, {self.metrics})"
