from flink_tpu.api.windowing import (
    TumblingEventTimeWindows,
    SlidingEventTimeWindows,
    EventTimeSessionWindows,
    GlobalWindows,
    TimeWindow,
    Trigger,
    EventTimeTrigger,
    CountTrigger,
    PurgingTrigger,
)
from flink_tpu.api.functions import (
    MapFunction,
    FilterFunction,
    FlatMapFunction,
    ReduceFunction,
    AggregateFunction,
    ProcessWindowFunction,
)

__all__ = [
    "TumblingEventTimeWindows",
    "SlidingEventTimeWindows",
    "EventTimeSessionWindows",
    "GlobalWindows",
    "TimeWindow",
    "Trigger",
    "EventTimeTrigger",
    "CountTrigger",
    "PurgingTrigger",
    "MapFunction",
    "FilterFunction",
    "FlatMapFunction",
    "ReduceFunction",
    "AggregateFunction",
    "ProcessWindowFunction",
]
