"""Sources — bounded and unbounded microbatch producers.

ref: the FLIP-27 split-based Source API (flink-core/.../api/connector/
source/{Source,SourceReader,SplitEnumerator}.java) and the legacy
SourceFunction. TPU-first redesign: a source yields **host numpy
microbatches** (struct-of-arrays + timestamps); splits map to generator
shards so a source can be partitioned across host runners. Checkpointing
a source = recording each split's replay position (the exactly-once
contract: replayable sources, SURVEY §8.4 item 5).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

Batch = Tuple[Dict[str, np.ndarray], np.ndarray]  # (data fields, timestamps)


def source_is_bounded(source: "Source") -> bool:
    """Boundedness of a source instance (ref: Boundedness.BOUNDED /
    CONTINUOUS_UNBOUNDED). The framework's sources all declare
    ``bounded`` as a property; USER-defined sources sometimes spell it
    as a plain method, which this tolerates rather than treating the
    bound method object as truthy."""
    b = source.bounded
    return bool(b() if callable(b) else b)


class Source:
    """A source produces numbered microbatches per split; position = batch
    index within the split (replay = start from a position)."""

    def declared_schema(self) -> Optional[Dict[str, str]]:
        """The record schema this source emits — field name → numpy
        dtype name — or None when it cannot be known without running
        (the plan analyzer's dataflow plane seeds schema propagation
        here; analysis/dataflow.py). Declaring is optional but a source
        with no schema makes every downstream field-reference check a
        no-op."""
        return None

    def splits(self) -> List[str]:
        return ["0"]

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        """Yield (data, timestamps) batches from ``start_pos`` on.
        A bounded split's iterator just ends (ref: Boundedness)."""
        raise NotImplementedError

    def position_after(self, pos: int, data, ts) -> int:
        """Replay position after consuming ONE batch that started at
        ``pos`` — positions are SOURCE-defined, not framework-defined
        (the FLIP-27 split-state principle: a Kafka-style source
        checkpoints offsets, a file source checkpoints batch indices).
        The default counts batches; offset-addressed sources
        (log.LogSource) return ``pos + rows`` instead, so a restore
        resumes mid-partition at an exact record offset."""
        return pos + 1

    @property
    def bounded(self) -> bool:
        return True


@dataclasses.dataclass
class CollectionSource(Source):
    """In-memory bounded source (ref: StreamExecutionEnvironment
    .fromCollection / fromData). Splits rows into microbatches of
    ``batch_size``."""

    data: Mapping[str, np.ndarray]
    timestamps: np.ndarray
    batch_size: int = 8192

    def declared_schema(self) -> Optional[Dict[str, str]]:
        # exact by construction: the collection IS the stream
        return {k: str(np.asarray(v).dtype) for k, v in self.data.items()}

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        n = len(self.timestamps)
        starts = range(start_pos * self.batch_size, n, self.batch_size)
        for s in starts:
            e = min(s + self.batch_size, n)
            yield (
                {k: np.asarray(v[s:e]) for k, v in self.data.items()},
                np.asarray(self.timestamps[s:e], dtype=np.int64),
            )


@dataclasses.dataclass
class DeviceGeneratorSource(Source):
    """Generator source whose batches can be synthesized ON the
    accelerator, chained directly into the consuming window operator's
    step program (the operator-chaining principle — ref: chained
    operators elide serialization, StreamingJobGraphGenerator chaining;
    flink-connector-datagen as the embedded-source role — taken to its
    TPU conclusion: the 'exchange' between source and operator is
    device registers, not even host memory).

    Contract: ``device_keys_ts(batch_index)`` (jax-traceable, i64
    scalar → (keys, ts) device arrays) and ``keys_ts_host(i)`` (numpy)
    must be BIT-EXACT for the same index — the host copy repairs
    device-side key-table misses and replays after restore.
    ``gen(split, i)`` materializes the full field set for consumers the
    chain can't host (non-count aggregates, multi-op fan-out, DCN).
    ``ts_bounds(i)`` returns the batch's exact (min_ts, max_ts) so the
    driver can run the watermark clock without touching the device."""

    gen: Callable[[str, int], Optional[Batch]]
    device_keys_ts: Callable = None
    keys_ts_host: Callable = None
    ts_bounds: Callable = None
    key_field: str = "key"
    batch_size: int = 8192
    n_batches: int = 0
    is_bounded: bool = True
    # bounded key domain [0, key_domain): REQUIRED for device chaining —
    # on device, key→slot must be a pure function (dense identity; see
    # KeyDirectory.register_dense), because table probes measured
    # pathological there. Records outside the domain are repaired
    # host-side. Dictionary-encoded keys (this framework's string
    # convention) fit naturally; None disables the device chain.
    key_domain: Optional[int] = None
    # PROVEN bound: the generator guarantees every key lies in
    # [0, key_domain) by construction (e.g. a multiply-shift range
    # reduction). Lets the operator skip the per-step stats round trip
    # when the batch's pane bounds also rule out late/refire work —
    # one fewer device→host transfer per microbatch on the relay.
    keys_bounded: bool = False
    # sub-batch re-slicing (pipeline.sub-batches, the fire/emit
    # decoupling knob): a callable ``k -> DeviceGeneratorSource`` whose
    # result produces the IDENTICAL record stream at batch_size/k
    # granularity — sub-batch j of logical batch i must be batch
    # i*k + j of the returned source, bit-exact slice [j*b', (j+1)*b')
    # of the logical batch. None = the source cannot subdivide; the
    # driver then keeps its device chain at logical granularity.
    subdivide: Optional[Callable[[int], "DeviceGeneratorSource"]] = None
    # declared record schema (field → numpy dtype name) of ``gen``'s
    # batches; seeds the analyzer's schema lattice (declared_schema)
    schema: Optional[Dict[str, str]] = None

    def declared_schema(self) -> Optional[Dict[str, str]]:
        return dict(self.schema) if self.schema is not None else None

    def subdivided(self, k: int) -> "DeviceGeneratorSource":
        """The equivalent source at batch_size/k granularity (see
        ``subdivide``). Raises when the source declares no subdivision
        or the batch size does not split evenly — callers decide
        whether that is a config error or a fallback."""
        if k < 1:
            raise ValueError(f"sub-batch count must be >= 1, got {k}")
        if k == 1:
            return self
        if self.subdivide is None:
            raise ValueError(
                "this DeviceGeneratorSource declares no subdivide "
                "callable — it cannot re-slice its stream")
        if self.batch_size % k:
            raise ValueError(
                f"pipeline.sub-batches={k} does not divide the device "
                f"source's batch_size={self.batch_size}")
        return self.subdivide(k)

    def splits(self) -> List[str]:
        return ["0"]  # device chaining is single-split by construction

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        i = start_pos
        while True:
            b = self.gen(split, i)
            if b is None:
                return
            yield b
            i += 1

    @property
    def bounded(self) -> bool:
        return self.is_bounded


@dataclasses.dataclass
class GeneratorSource(Source):
    """Rate-unbounded generator source (ref: flink-connector-datagen
    DataGeneratorSource). ``gen(split, batch_index)`` returns a batch or
    None for end-of-split — deterministic in (split, index) so replay
    after failure reproduces the stream exactly (the replayable-source
    contract)."""

    gen: Callable[[str, int], Optional[Batch]]
    n_splits: int = 1
    is_bounded: bool = True
    # declared record schema (field → numpy dtype name); None = opaque
    # generator — downstream schema checks stay silent
    schema: Optional[Dict[str, str]] = None

    def declared_schema(self) -> Optional[Dict[str, str]]:
        return dict(self.schema) if self.schema is not None else None

    def splits(self) -> List[str]:
        return [str(i) for i in range(self.n_splits)]

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        i = start_pos
        while True:
            b = self.gen(split, i)
            if b is None:
                return
            yield b
            i += 1

    @property
    def bounded(self) -> bool:
        return self.is_bounded


@dataclasses.dataclass
class TextLineSource(Source):
    """Line-oriented file source (ref: flink-connector-files FileSource +
    TextLineInputFormat). Emits a single string column ``line`` (object
    dtype — host-only; a tokenize/encode map must run before any device
    op) with ingest-time timestamps."""

    path: str
    batch_size: int = 8192

    def declared_schema(self) -> Optional[Dict[str, str]]:
        return {"line": "object"}

    def open_split(self, split: str, start_pos: int = 0) -> Iterator[Batch]:
        import time

        with open(self.path, "r", encoding="utf-8") as f:
            batch: List[str] = []
            index = 0
            for line in f:
                batch.append(line.rstrip("\n"))
                if len(batch) == self.batch_size:
                    if index >= start_pos:
                        now = np.int64(time.time() * 1000)
                        yield ({"line": np.array(batch, dtype=object)},
                               np.full(len(batch), now, dtype=np.int64))
                    index += 1
                    batch = []
            if batch and index >= start_pos:
                now = np.int64(time.time() * 1000)
                yield ({"line": np.array(batch, dtype=object)},
                       np.full(len(batch), now, dtype=np.int64))
