"""The fluent DataStream API.

ref: streaming/api/datastream/{DataStream,KeyedStream,WindowedStream,
DataStreamSource,SingleOutputStreamOperator,JoinedStreams}.java — the
reference's primary user API. Each call appends a Transformation; nothing
runs until ``StreamExecutionEnvironment.execute()``.

TPU-first deltas: user functions are jax-traceable **batch** functions
over struct-of-arrays dicts (fused into one compiled step per stage, the
chaining analogue), filter is a validity-mask AND (no compaction under
jit), flat_map has a static max fan-out, and keys are int64 columns
(strings must be dictionary-encoded in a prior map — strings never reach
the device).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import numpy as np

from flink_tpu.api.windowing import (
    EventTimeSessionWindows,
    Trigger,
    WindowAssigner,
)
from flink_tpu.graph.transformations import (
    KeyByTransformation,
    MapTransformation,
    SessionAggregateTransformation,
    SinkTransformation,
    Transformation,
    UnionTransformation,
    WindowAggregateTransformation,
    BroadcastConnectTransformation,
    WindowJoinTransformation,
)
from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.time.watermarks import WatermarkStrategy


class DataStream:
    """ref: streaming/api/datastream/DataStream.java"""

    def __init__(self, env: "StreamExecutionEnvironment", transform: Transformation):
        self.env = env
        self.transform = transform

    # -- stateless ops (chained) -----------------------------------------
    def map(self, fn: Callable, name: str = "map") -> "DataStream":
        """``fn(data_dict) -> data_dict`` over (B,) field arrays —
        jax-traceable, traced once into the stage step function
        (ref: DataStream.map → StreamMap)."""

        def op(data, ts, valid):
            return fn(data), ts, valid

        return self._append(MapTransformation(name, (self.transform,), fn=op, kind="map"))

    def map_with_timestamps(self, fn: Callable, name: str = "map_ts") -> "DataStream":
        """``fn(data, ts, valid) -> (data, ts, valid)`` — full-control map
        (reassign timestamps, e.g. event-time extraction)."""
        return self._append(MapTransformation(name, (self.transform,), fn=fn, kind="map"))

    def filter(self, pred: Callable, name: str = "filter") -> "DataStream":
        """``pred(data_dict) -> (B,) bool`` (ref: DataStream.filter →
        StreamFilter). Lowered to a validity-mask AND."""

        def op(data, ts, valid):
            return data, ts, valid & pred(data)

        return self._append(MapTransformation(name, (self.transform,), fn=op, kind="filter"))

    def flat_map(self, fn: Callable, name: str = "flat_map") -> "DataStream":
        """``fn(data, ts, valid) -> (data', ts', valid')`` with any output
        length (ref: DataStream.flatMap → StreamFlatMap). Ingest chains
        execute on the HOST (numpy), so fan-out is unconstrained here;
        only device-fused functions need the static-fan-out form
        (api/functions.FlatMapFunction.max_fanout)."""
        return self._append(MapTransformation(name, (self.transform,), fn=fn, kind="flatmap"))

    def assign_timestamps_and_watermarks(
        self, strategy: WatermarkStrategy, ts_field: Optional[str] = None,
        name: str = "assign_ts",
    ) -> "DataStream":
        """ref: DataStream.assignTimestampsAndWatermarks. With ts_field,
        record timestamps are re-read from that column."""
        self.env._watermark_strategy = strategy
        if ts_field is None:
            return self

        def op(data, ts, valid):
            return data, data[ts_field].astype(np.int64), valid

        return self._append(MapTransformation(name, (self.transform,), fn=op, kind="map"))

    def union(self, *others: "DataStream") -> "DataStream":
        inputs = (self.transform,) + tuple(o.transform for o in others)
        return self._append(UnionTransformation("union", inputs))

    # -- keying ----------------------------------------------------------
    def key_by(self, key: Union[str, Callable], name: str = "keyBy") -> "KeyedStream":
        """ref: DataStream.keyBy → KeyedStream. ``key`` is an int64 column
        name, or a device fn(data_dict)->(B,) int64 evaluated in-stage."""
        if callable(key):
            t = KeyByTransformation(name, (self.transform,), key_field="__key__", key_fn=key)
            t.key_field = f"__key_{t.id}__"  # unique per keyBy: two keyBys
            # off one stream must not clobber each other's derived column
        else:
            t = KeyByTransformation(name, (self.transform,), key_field=key)
        self.env._register(t)
        return KeyedStream(self.env, t)

    def async_io(self, fn: Any, capacity: int = 8,
                 timeout_ms: int = 60_000, ordered: bool = True,
                 name: str = "async_io") -> "DataStream":
        """Async external enrichment (ref: AsyncDataStream.orderedWait /
        unorderedWait). ``fn`` is an api.functions-style AsyncFunction
        (invoke_batch) or a plain callable ``(data, ts) -> data'`` doing
        the external lookup for a whole microbatch; up to ``capacity``
        batches overlap on a worker pool while ingest continues.
        ``ordered=False`` releases batches as they complete; watermarks
        never overtake pending batches either way."""
        from flink_tpu.graph.transformations import AsyncIOTransformation

        return self._append(AsyncIOTransformation(
            name, (self.transform,), fn=fn, capacity=capacity,
            timeout_ms=timeout_ms, ordered=ordered))

    # -- non-keyed partitioning (ref: DataStream.{rebalance,rescale,
    # shuffle,broadcast,global} → PartitionTransformation) --------------
    def rebalance(self) -> "DataStream":
        """Round-robin across parallel subtasks — exact equal spread."""
        return self._partition("rebalance")

    def rescale(self) -> "DataStream":
        """Round-robin within the local scale group (never cross-host)."""
        return self._partition("rescale")

    def shuffle(self) -> "DataStream":
        """Uniform-random subtask per record (seeded → replay-stable)."""
        return self._partition("shuffle")

    def broadcast(self) -> "DataStream":
        """Replicate every record to every subtask."""
        return self._partition("broadcast")

    def global_(self) -> "DataStream":
        """Send everything to subtask 0 (trailing underscore: ``global``
        is a Python keyword)."""
        return self._partition("global")

    def _partition(self, strategy: str) -> "DataStream":
        from flink_tpu.graph.transformations import PartitionTransformation

        return self._append(PartitionTransformation(
            strategy, (self.transform,), strategy=strategy))

    def window_all(self, assigner: WindowAssigner) -> "AllWindowedStream":
        """Global (non-keyed) window over ALL records (ref: DataStream.
        windowAll → AllWindowedStream). Lowered without the reference's
        parallelism-1 funnel — see ops/window_all.py."""
        return AllWindowedStream(self, assigner)

    # -- joins -----------------------------------------------------------
    def join(self, other: "DataStream") -> "JoinBuilder":
        """ref: DataStream.join → JoinedStreams (where/equalTo/window)."""
        return JoinBuilder(self, other)

    def connect(self, broadcast: "DataStream") -> "BroadcastConnectedStream":
        """Connect THIS (data) stream with a low-volume CONTROL stream
        whose elements replicate into broadcast state (ref: DataStream
        .connect(BroadcastStream) → BroadcastConnectedStream; the
        broadcast state pattern). ``.process(fn)`` with a
        BroadcastProcessFunction completes the pair."""
        return BroadcastConnectedStream(self, broadcast)

    # -- sinks -----------------------------------------------------------
    def add_sink(self, sink: Any, name: str = "sink") -> "DataStream":
        return self._append(SinkTransformation(name, (self.transform,), sink=sink))

    def print(self, prefix: str = "", limit: Optional[int] = None) -> "DataStream":
        from flink_tpu.api.sinks import PrintSink

        return self.add_sink(PrintSink(prefix, limit), name="print")

    def collect(self) -> "Any":
        """Attach a CollectSink and return it (materializes at execute();
        ref: DataStream.executeAndCollect)."""
        from flink_tpu.api.sinks import CollectSink

        sink = CollectSink()
        self.add_sink(sink, name="collect")
        return sink

    def _append(self, t: Transformation) -> "DataStream":
        self.env._register(t)
        return DataStream(self.env, t)


class KeyedStream(DataStream):
    """ref: streaming/api/datastream/KeyedStream.java"""

    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        if isinstance(assigner, EventTimeSessionWindows):
            return SessionWindowedStream(self, assigner)
        return WindowedStream(self, assigner)

    def count_window(self, size: int) -> "CountWindowedStream":
        """Fires every ``size`` elements per key (ref: KeyedStream.
        countWindow = GlobalWindows + PurgingTrigger(CountTrigger)).
        Trigger evaluation is per microbatch — see ops/count_window.py
        for the documented batching semantics."""
        return CountWindowedStream(self, size, purge=True)

    def running_aggregate(self, agg, name: str = "running_agg",
                          retract: bool = False) -> "DataStream":
        """Unwindowed keyed running aggregation emitting an UPSERT
        stream: each microbatch emits updated (key, aggregates) rows
        for every key it touched, each row replacing the previous one
        for its key (ref: table-runtime GroupAggFunction — the
        retract/changelog model degenerated to upserts for insert-only
        input; see ops/global_agg.py). Materialize latest-by-key with
        ``UpsertSink``.

        ``retract=True`` emits the full CHANGELOG instead: updates
        become -U (stale row out) / +U (replacement in) pairs, first
        results are +I, op-typed in the ``__op__`` int8 column
        (records.OP_FIELD). Downstream consumers must fold retractions
        — ``RetractSink`` materializes exactly-once, and the
        ``changelog_*`` lanes of ops/aggregates.py subtract -U rows in
        a downstream window aggregation."""
        from flink_tpu.graph.transformations import (
            GlobalAggregateTransformation)

        kt = self.transform
        t = GlobalAggregateTransformation(
            name, (kt,), aggregate=agg, key_field=kt.key_field,
            retract=retract)
        self.env._register(t)
        return DataStream(self.env, t)

    def process(self, fn: Any, name: str = "keyed_process") -> "DataStream":
        """General keyed processing with state + timers (ref: KeyedStream
        .process(KeyedProcessFunction)). ``fn`` implements
        api.functions.KeyedProcessFunction — batch-vectorized hooks, or
        the per-element adapter."""
        from flink_tpu.graph.transformations import KeyedProcessTransformation

        kt = self.transform
        assert isinstance(kt, KeyByTransformation)
        t = KeyedProcessTransformation(
            name, (kt,), fn=fn, key_field=kt.key_field)
        self.env._register(t)
        return DataStream(self.env, t)

    # keyed reduce without windows = running aggregate over an eternal
    # window; expressible via GlobalWindows + custom trigger (later).


class _AggregateShortcuts:
    """count/sum/max/min sugar shared by every windowed-stream flavor;
    each delegates to the subclass's aggregate()."""

    def count(self):
        from flink_tpu.ops.aggregates import count as count_agg

        return self.aggregate(count_agg())

    def sum(self, field: str):
        from flink_tpu.ops.aggregates import sum_of

        return self.aggregate(sum_of(field))

    def max(self, field: str):
        from flink_tpu.ops.aggregates import max_of

        return self.aggregate(max_of(field))

    def min(self, field: str):
        from flink_tpu.ops.aggregates import min_of

        return self.aggregate(min_of(field))


class WindowedStream(_AggregateShortcuts):
    """ref: streaming/api/datastream/WindowedStream.java"""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self.keyed = keyed
        self.assigner = assigner
        self._lateness = 0
        self._trigger: Optional[Trigger] = None
        self._evictor = None

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._lateness = ms
        return self

    def trigger(self, trigger: Trigger) -> "WindowedStream":
        self._trigger = trigger
        return self

    def evictor(self, evictor) -> "WindowedStream":
        """ref: WindowedStream.evictor — routes the window onto the
        element-buffer operator (ops/evicting_window.py): eviction
        needs the window's elements at fire time, which the incremental
        pane kernels never materialize (the reference pays the same
        price — EvictingWindowOperator switches to ListState)."""
        self._evictor = evictor
        return self

    def _element_path(self) -> bool:
        """True when this window must run on the element-buffer
        operator: an evictor is set, or the trigger is outside the
        vectorized families (user Trigger subclasses, CountTrigger on
        time windows — exact per-element semantics)."""
        from flink_tpu.api.windowing import (
            EventTimeTrigger, ProcessingTimeTrigger, PurgingTrigger)

        if getattr(self, "_evictor", None) is not None:
            return True
        t = self._trigger
        if t is None or isinstance(t, (EventTimeTrigger,
                                       ProcessingTimeTrigger)):
            return False
        if isinstance(t, PurgingTrigger) and isinstance(
                t.inner, EventTimeTrigger) and self._lateness == 0:
            return False
        return True

    def apply(self, window_fn, name: str = "evicting_window") -> DataStream:
        """Element-path window function: ``window_fn(elements)`` sees
        the window's surviving elements (field arrays + ``__ts__``)
        and returns the result row's fields (ref: WindowFunction.apply
        over the evicted iterable)."""
        self._check_element_path()
        kt = self.keyed.transform
        assert isinstance(kt, KeyByTransformation)
        from flink_tpu.graph.transformations import (
            EvictingWindowTransformation)

        t = EvictingWindowTransformation(
            name, (kt,), assigner=self.assigner, window_fn=window_fn,
            trigger=self._trigger, evictor=getattr(self, "_evictor", None),
            allowed_lateness_ms=self._lateness, key_field=kt.key_field)
        self.keyed.env._register(t)
        return DataStream(self.keyed.env, t)

    def _check_element_path(self) -> None:
        """Validate combinations BEFORE building an element-buffer
        operator: that operator assigns windows by event timestamps and
        fires on the event watermark, so a processing-time assigner or
        ProcessingTimeTrigger here would silently produce wrong results
        (the pane path's _check_trigger rejects these; the element path
        must too)."""
        from flink_tpu.api.windowing import (
            ProcessingTimeTrigger, PurgingTrigger)

        if bool(getattr(self.assigner, "is_processing_time", False)):
            raise NotImplementedError(
                "processing-time window assigners are not supported on "
                "the element-buffer (evictor/custom-trigger) path — it "
                "assigns and fires on event time; use an event-time "
                "assigner or drop the evictor/custom trigger")
        t = self._trigger
        inner = t.inner if isinstance(t, PurgingTrigger) else t
        if isinstance(inner, ProcessingTimeTrigger):
            raise NotImplementedError(
                "ProcessingTimeTrigger is not supported on the element-"
                "buffer (evictor/custom-trigger) path — fires are "
                "driven by the event watermark there")

    def _check_trigger(self) -> None:
        """Validate the trigger/window combination at build time —
        unsupported combinations must raise, never be silently ignored
        (ref: WindowedStream.trigger contract)."""
        from flink_tpu.api.windowing import (
            CountTrigger, EventTimeTrigger, ProcessingTimeTrigger,
            PurgingTrigger)

        proc_assigner = bool(getattr(self.assigner, "is_processing_time",
                                     False))
        if proc_assigner and self._lateness:
            raise NotImplementedError(
                "allowed lateness is an event-time concept; processing-"
                "time windows cannot see late records (ref: "
                "WindowedStream.allowedLateness is event-time only)")
        t = self._trigger
        if isinstance(t, ProcessingTimeTrigger):
            if proc_assigner:
                return  # the proc-time assigners' default trigger
            raise NotImplementedError(
                "ProcessingTimeTrigger requires a processing-time window "
                "assigner (Tumbling/SlidingProcessingTimeWindows)")
        if proc_assigner and isinstance(t, EventTimeTrigger):
            raise NotImplementedError(
                "EventTimeTrigger on processing-time windows is not "
                "supported — the window's time axis is the clock")
        if t is None or isinstance(t, EventTimeTrigger):
            return
        if isinstance(t, PurgingTrigger) and isinstance(
                t.inner, EventTimeTrigger):
            # FIRE_AND_PURGE at the watermark: with zero allowed
            # lateness the window's state is purged at its lateness
            # horizon — i.e. AT the fire — so the purging wrapper is
            # exactly the default behavior. With lateness it would
            # change late-record semantics (fresh state instead of
            # re-aggregation), which the pane backend doesn't express.
            if self._lateness == 0:
                return
            raise NotImplementedError(
                "PurgingTrigger(EventTimeTrigger) with allowed lateness "
                "> 0 is not supported (late records would need "
                "fresh-state semantics); drop the lateness or the "
                "purging wrapper")
        inner = t.inner if isinstance(t, PurgingTrigger) else t
        if isinstance(inner, CountTrigger):
            raise NotImplementedError(
                "count triggers on time windows are not supported; use "
                "key_by(...).count_window(n) (GlobalWindows + "
                "CountTrigger, the reference's countWindow lowering)")
        raise NotImplementedError(
            f"unsupported trigger {type(t).__name__} for time windows")

    def aggregate(self, agg: LaneAggregate, name: str = "window_agg") -> "WindowedAggregateStream":
        """ref: WindowedStream.aggregate(AggregateFunction) — but taking
        the lane-lowered form directly; ``lower_aggregate`` adapts
        reference-style AggregateFunction classes."""
        if self._element_path():
            return self.apply(_element_window_fn(agg), name=name)
        self._check_trigger()
        kt = self.keyed.transform
        assert isinstance(kt, KeyByTransformation)
        t = WindowAggregateTransformation(
            name, (kt,),
            assigner=self.assigner, aggregate=agg, trigger=self._trigger,
            allowed_lateness_ms=self._lateness, key_field=kt.key_field)
        self.keyed.env._register(t)
        return WindowedAggregateStream(self.keyed.env, t)



def _element_window_fn(agg: LaneAggregate):
    """Adapt a LaneAggregate to the element-path window-function
    contract: reduce the surviving elements' lifted lanes and finalize.
    Host-side per (key, window) — the compatibility path's cost."""
    import numpy as np

    def fn(elements):
        data = {k: v for k, v in elements.items() if k != "__ts__"}
        n = len(elements["__ts__"])
        import jax.numpy as jnp

        s, mx, mn = agg.lift_masked(
            {k: jnp.asarray(np.asarray(v)) for k, v in data.items()},
            jnp.ones(n, bool))
        res = agg.finalize(jnp.sum(s, axis=0), jnp.max(mx, axis=0),
                           jnp.min(mn, axis=0), jnp.asarray(n, jnp.int32))
        return {k: np.asarray(v) for k, v in res.items()}

    return fn


class AllWindowedStream(_AggregateShortcuts):
    """ref: streaming/api/datastream/AllWindowedStream.java"""

    def __init__(self, stream: DataStream, assigner: WindowAssigner):
        self.stream = stream
        self.assigner = assigner
        self._lateness = 0

    def allowed_lateness(self, ms: int) -> "AllWindowedStream":
        self._lateness = ms
        return self

    def aggregate(self, agg: LaneAggregate,
                  name: str = "window_all_agg") -> DataStream:
        from flink_tpu.graph.transformations import (
            WindowAllAggregateTransformation)

        t = WindowAllAggregateTransformation(
            name, (self.stream.transform,), assigner=self.assigner,
            aggregate=agg, allowed_lateness_ms=self._lateness)
        self.stream.env._register(t)
        return DataStream(self.stream.env, t)


class CountWindowedStream(_AggregateShortcuts):
    """ref: KeyedStream.countWindow — GlobalWindows + (Purging)Count
    trigger, lowered to the vectorized per-step mask (ops/count_window)."""

    def __init__(self, keyed: KeyedStream, size: int, purge: bool = True):
        self.keyed = keyed
        self.size = size
        self.purge = purge

    def aggregate(self, agg: LaneAggregate,
                  name: str = "count_window_agg") -> DataStream:
        from flink_tpu.graph.transformations import (
            CountWindowAggregateTransformation)

        kt = self.keyed.transform
        assert isinstance(kt, KeyByTransformation)
        t = CountWindowAggregateTransformation(
            name, (kt,), size=self.size, purge=self.purge,
            aggregate=agg, key_field=kt.key_field)
        self.keyed.env._register(t)
        return DataStream(self.keyed.env, t)



class WindowedAggregateStream(DataStream):
    """The stream of fired (key, window, result...) rows. Exposes
    post-aggregation shapes that FUSE into the window operator's device
    fire path instead of running on the host."""

    def top(self, n: int, by: Optional[str] = None,
            name: str = "window_top") -> DataStream:
        """Keep only each window's top-``n`` rows ranked by result field
        ``by`` (ties at the n-th value kept — SQL RANK() <= n, the
        Nexmark Q5 hot-items shape). Evaluated ON DEVICE inside the fire
        kernel, so only winners ever cross to the host — the whole
        per-key result set stays in HBM. ``by`` defaults to the
        aggregate's single result field."""
        t = self.transform
        if by is None:
            from flink_tpu.ops.aggregates import result_fields

            fields = result_fields(t.aggregate)
            if len(fields) != 1:
                raise ValueError(
                    f"aggregate produces {fields}; pass by= explicitly")
            by = fields[0]
        t.top_n = (by, n)
        return self


class SessionWindowedStream(WindowedStream):
    def aggregate(self, agg: LaneAggregate, name: str = "session_agg",
                  retract: bool = False) -> DataStream:
        """``retract=True``: session-merge refires op-type their rows —
        a merge consuming an already-fired span emits -U for the stale
        (key, window) row before the merged session fires +I/+U (see
        ops/session.py retract mode)."""
        self._check_trigger()
        kt = self.keyed.transform
        assert isinstance(kt, KeyByTransformation)
        t = SessionAggregateTransformation(
            name, (kt,), gap_ms=self.assigner.gap, aggregate=agg,
            allowed_lateness_ms=self._lateness, key_field=kt.key_field,
            retract=retract)
        self.keyed.env._register(t)
        return DataStream(self.keyed.env, t)


class JoinBuilder:
    """where/equalTo/window/apply chain (ref: JoinedStreams.java)."""

    def __init__(self, left: DataStream, right: DataStream):
        self._left = left
        self._right = right
        self._left_key: Optional[str] = None
        self._right_key: Optional[str] = None

    def where(self, key_field: str) -> "JoinBuilder":
        self._left_key = key_field
        return self

    def equal_to(self, key_field: str) -> "JoinBuilder":
        self._right_key = key_field
        return self

    def window(self, assigner: WindowAssigner) -> "WindowedJoin":
        return WindowedJoin(self, assigner)


class WindowedJoin:
    def __init__(self, builder: JoinBuilder, assigner: WindowAssigner):
        self.b = builder
        self.assigner = assigner

    def apply(
        self,
        left_fields: Sequence[str] = (),
        right_fields: Sequence[str] = (),
        name: str = "window_join",
        mode: str = "pairs",
    ) -> DataStream:
        """``mode='pairs'`` (default): one row per matching left×right
        pair — the reference's exact JoinFunction semantics.
        ``mode='aggregate'``: one row per (key, window) present on both
        sides with per-side count + max-carried fields (cogroup-style
        summary). See ops/join.py."""
        env = self.b._left.env
        t = WindowJoinTransformation(
            name, (self.b._left.transform, self.b._right.transform),
            assigner=self.assigner,
            left_key=self.b._left_key or "key",
            right_key=self.b._right_key or "key",
            left_fields=tuple(left_fields), right_fields=tuple(right_fields),
            mode=mode)
        env._register(t)
        return DataStream(env, t)


class BroadcastConnectedStream:
    """ref: BroadcastConnectedStream — the (data, control) pair awaiting
    its BroadcastProcessFunction."""

    def __init__(self, data: DataStream, control: DataStream) -> None:
        self._data = data
        self._control = control

    def process(self, fn: Any,
                name: str = "broadcast_connect") -> DataStream:
        t = BroadcastConnectTransformation(
            name, (self._data.transform, self._control.transform), fn=fn)
        self._data.env._register(t)
        return DataStream(self._data.env, t)
