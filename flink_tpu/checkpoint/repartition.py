"""Key-group state repartition for process-level rescale (N -> M).

ref role: StateAssignmentOperation — on rescale the reference re-splits
every operator's key-group ranges across the new subtask set. Here the
key-group space is ``state.num-key-shards`` (fixed, the maxParallelism
contract) and a PROCESS owns a contiguous shard span [p*spp, (p+1)*spp);
rescaling from N_old to N_new processes therefore moves whole shard
spans, never single keys (exchange/partitioners.hybrid_route is the one
routing truth both planes share).

The unit of work is a SAVEPOINT SET: one self-contained savepoint per
OLD process, all taken at the same DCN rendezvous barrier (a globally
consistent cut). ``merge_payloads`` fuses the set into ONE driver
payload restorable by a single NEW process — called once per new
process, each call slicing its own key-group range out of the merged
global state.

Merge rules by operator layout:

- device window ops (factory kind "window"): pane arrays are blocked
  per device (n_dev blocks of slots_local+1 rows, the +1 a dump row).
  De-block each payload, concatenate the old processes' shard spans
  into the global logical slot axis, slice the new range, and emit as
  one n_dev=1 block with a fresh dump row — restore_state re-blocks to
  the restoring mesh's device count (``_reblock_panes``).
- full-width slot ops (process, cep, count_window, global_agg, and the
  window sides of an aggregate-mode join): arrays span ALL shards but
  each old process only populated its own span — splice the owner's
  span per shard range.
- columnar host state (session columns, pairs-join side buffers,
  evicting-window bufs): concatenate rows and keep only keys whose
  shard (splitmix64 % num_shards) lands in the new range.
- KeyDirectory: rev arrays merge at the snapshot level (they are
  shard-major, so spans splice contiguously); next_free is global
  shard-indexed and splices per span. No directory code changes.
- timers (KeyedProcessOperator): slots are global (shard*sps + ix) and
  survive the splice unchanged; filtering to the new range is what
  prevents two new processes from both firing the same key's timer.

RAM-spilled window state (state.backend='spill' with live host panes)
does not repartition — the spill ledger is keyed by local pane id and
has no shard-major layout to splice; merge_payloads raises rather than
silently dropping it (see COMPONENTS.md for the residue). The DISK
tier (state.backend='lsm') DOES repartition: run rows carry their
key-group shard, so the merge filters each old process's runs + delta
to the new range and emits a pure-delta lsm snapshot
(_merge_lsm_spill / state/lsm.py merge_rescale_spill).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.exchange.partitioners import hash_shards

__all__ = ["merge_payloads", "RescaleError"]


class RescaleError(RuntimeError):
    """A savepoint set that cannot be repartitioned (geometry mismatch,
    unsupported state layout). Deliberately loud: a silent partial merge
    would drop keyed state."""


class _Geo:
    """Shard-space geometry shared by every merge rule."""

    def __init__(self, n_old: int, new_pid: int, new_nproc: int,
                 num_shards: int, slots_per_shard: int) -> None:
        if num_shards % n_old or num_shards % new_nproc:
            raise RescaleError(
                f"state.num-key-shards ({num_shards}) must divide by both "
                f"the old ({n_old}) and new ({new_nproc}) process counts")
        self.n_old = n_old
        self.num_shards = num_shards
        self.sps = slots_per_shard
        self.spp_old = num_shards // n_old
        spp_new = num_shards // new_nproc
        self.new_lo = new_pid * spp_new
        self.new_hi = (new_pid + 1) * spp_new
        self.R = num_shards * slots_per_shard

    # slot-axis span of old process o (global slot ids)
    def slot_span(self, o: int):
        return o * self.spp_old * self.sps, (o + 1) * self.spp_old * self.sps

    # shard-axis span of old process o
    def shard_span(self, o: int):
        return o * self.spp_old, (o + 1) * self.spp_old

    @property
    def tgt_slot_lo(self) -> int:
        return self.new_lo * self.sps

    @property
    def tgt_slot_hi(self) -> int:
        return self.new_hi * self.sps


def _splice_slots(arrs: Sequence[np.ndarray], g: _Geo) -> np.ndarray:
    """Full-width slot-indexed arrays (first dim == num_shards*sps):
    take each old owner's populated span, in shard order."""
    parts = []
    for o, a in enumerate(arrs):
        a = np.asarray(a)
        if a.shape[0] != g.R:
            raise RescaleError(
                f"slot array of length {a.shape[0]} != num_shards * "
                f"slots_per_shard ({g.R}) — geometry drifted across the "
                "savepoint set")
        lo, hi = g.slot_span(o)
        parts.append(a[lo:hi])
    return np.concatenate(parts)


def _splice_shards(arrs: Sequence[np.ndarray], g: _Geo) -> np.ndarray:
    """Global shard-indexed arrays (length num_shards), e.g. the
    directory's next_free."""
    parts = []
    for o, a in enumerate(arrs):
        lo, hi = g.shard_span(o)
        parts.append(np.asarray(a)[lo:hi])
    return np.concatenate(parts)


def _clear_outside_range(arr: np.ndarray, lo: int, hi: int, fill) -> None:
    """Zero a merged global array outside the new process's span — keys
    there belong to a sibling; keeping them would double-count metrics
    (directory occupancy) or, for self-firing state, double-emit."""
    arr[:lo] = fill
    arr[hi:] = fill


def _opt_min(vals):
    vs = [v for v in vals if v is not None]
    return min(vs) if vs else None


def _opt_max(vals):
    vs = [v for v in vals if v is not None]
    return max(vs) if vs else None


# -- KeyDirectory ----------------------------------------------------------

def _merge_directory(snaps: Sequence[Dict[str, np.ndarray]], g: _Geo,
                     src_ranged: bool, tgt_ranged: bool) -> Dict[str, Any]:
    """Snapshot-level merge: rev arrays are shard-major so old spans
    concatenate into the global reverse map; restore() rebuilds the
    hash table from them (state/keyed.py), so no directory class change
    is needed."""
    if src_ranged:
        # each payload's rev arrays ARE its span, already in shard order
        rev_keys = np.concatenate([np.asarray(s["rev_keys"]) for s in snaps])
        rev_used = np.concatenate([np.asarray(s["rev_used"]) for s in snaps])
        if rev_keys.shape[0] != g.R:
            raise RescaleError(
                f"ranged directory spans sum to {rev_keys.shape[0]} slots, "
                f"expected {g.R}")
    else:
        rev_keys = _splice_slots([s["rev_keys"] for s in snaps], g)
        rev_used = _splice_slots([s["rev_used"] for s in snaps], g)
    next_free = _splice_shards([s["next_free"] for s in snaps], g)
    _clear_outside_range(next_free, g.new_lo, g.new_hi, 0)
    if tgt_ranged:
        rev_keys = rev_keys[g.tgt_slot_lo:g.tgt_slot_hi]
        rev_used = rev_used[g.tgt_slot_lo:g.tgt_slot_hi]
    else:
        _clear_outside_range(rev_keys, g.tgt_slot_lo, g.tgt_slot_hi, 0)
        _clear_outside_range(rev_used, g.tgt_slot_lo, g.tgt_slot_hi, False)
    return {"rev_keys": rev_keys, "rev_used": rev_used,
            "next_free": next_free}


# -- timers (KeyedProcessOperator) ----------------------------------------

def _merge_timers(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    slots_l: List[np.ndarray] = []
    ts_l: List[np.ndarray] = []
    for o, t in enumerate(snaps):
        s = np.asarray(t["slots"], np.int64)
        ts = np.asarray(t["ts"], np.int64)
        lo, hi = g.slot_span(o)
        m = (s >= lo) & (s < hi)  # a timer belongs to its slot's owner
        slots_l.append(s[m])
        ts_l.append(ts[m])
    s = np.concatenate(slots_l)
    ts = np.concatenate(ts_l)
    m = (s >= g.tgt_slot_lo) & (s < g.tgt_slot_hi)
    s, ts = s[m], ts[m]
    order = np.lexsort((s, ts))  # TimerService fire order: (ts, slot)
    return {"slots": s[order], "ts": ts[order], "deleted": []}


# -- per-kind operator merges ----------------------------------------------

def _deblock(arr: np.ndarray, n_dev: int) -> np.ndarray:
    """Drop each device block's dump row and concatenate the blocks
    back into the logical (total_slots, ...) axis (inverse of the
    per-block layout _reblock_panes emits)."""
    arr = np.asarray(arr)
    rpl = arr.shape[0] // n_dev
    return np.concatenate(
        [arr[d * rpl:(d + 1) * rpl - 1] for d in range(n_dev)])


_PANE_FILLS = {"sums": 0.0, "maxs": -np.inf, "mins": np.inf, "counts": 0}


def _merge_window(snaps: Sequence[Dict[str, Any]], g: _Geo,
                  tgt_ranged: bool) -> Dict[str, Any]:
    from flink_tpu.state.keyed import PaneState

    lsm_parts = []
    for s in snaps:
        sp = s.get("spill")
        if sp and sp.get("kind") == "lsm":
            # key-group-addressed tier (state/lsm.py): run rows carry
            # their shard, so the spill merges by filtering — see
            # _merge_lsm_spill below
            if int(sp.get("num_shards", g.num_shards)) != g.num_shards:
                raise RescaleError(
                    f"lsm spill was written with num_shards="
                    f"{sp['num_shards']} but the merge targets "
                    f"{g.num_shards} — state.num-key-shards is the "
                    "maxParallelism contract and cannot change")
            lsm_parts.append((sp, {**(sp.get("aux_files") or {}),
                                   **(s.get("__aux_files__") or {}),
                                   **(s.get("__aux_paths__") or {})}))
        elif sp and sp.get("panes"):
            raise RescaleError(
                "cannot repartition spilled window state "
                f"({len(sp['panes'])} live host pane(s)): the RAM spill "
                "ledger has no shard-major layout to re-split. Let the "
                "spill drain (lateness horizon) before rescaling, or "
                "use state.backend='lsm' (key-group-addressed runs "
                "rescale) or 'hbm'.")
    rings = sorted({int(s["ring"]) for s in snaps})
    if len(rings) != 1:
        raise RescaleError(
            f"pane rings diverged across the savepoint set ({rings}): an "
            "auto-grown ring is process-local and ring-indexed state "
            "cannot be spliced across geometries. Redeploy with the "
            "larger ring (raise allowed lateness) and re-savepoint.")
    per: List[Dict[str, Optional[np.ndarray]]] = []
    for s in snaps:
        pan = s["panes"]
        n_dev = int(s.get("n_dev", 1))
        per.append({f: (None if getattr(pan, f) is None
                        else _deblock(getattr(pan, f), n_dev))
                    for f in _PANE_FILLS})
    l0 = per[0]["counts"].shape[0]
    if l0 == g.R:
        src_ranged = False
    elif l0 == g.spp_old * g.sps:
        src_ranged = True
    else:
        raise RescaleError(
            f"window pane axis has {l0} logical slots; expected "
            f"{g.R} (full) or {g.spp_old * g.sps} (per-process span)")
    merged: Dict[str, Optional[np.ndarray]] = {}
    for f, fill in _PANE_FILLS.items():
        arrs = [d[f] for d in per]
        if arrs[0] is None:
            merged[f] = None
            continue
        if src_ranged:
            glob = np.concatenate(arrs)
        else:
            glob = _splice_slots(arrs, g)
        if tgt_ranged:
            glob = glob[g.tgt_slot_lo:g.tgt_slot_hi]
        dump = np.full((1,) + glob.shape[1:], fill, dtype=glob.dtype)
        merged[f] = np.concatenate([glob, dump])
    return {
        "spill": _merge_lsm_spill(lsm_parts, g),
        "n_dev": 1,  # restore re-blocks to the restoring mesh
        "ring": rings[0],
        "panes": PaneState(sums=merged["sums"], maxs=merged["maxs"],
                           mins=merged["mins"], counts=merged["counts"]),
        "directory": _merge_directory(
            [s["directory"] for s in snaps], g,
            src_ranged=src_ranged, tgt_ranged=tgt_ranged),
        # the cut is one rendezvous barrier, so the fleet agreed on the
        # clock; min/max below only matter for the data-dependent fields
        "watermark": min(s["watermark"] for s in snaps),
        "cleared_below": min(s["cleared_below"] for s in snaps),
        "fired_below_end": _opt_max(
            [s["fired_below_end"] for s in snaps]),
        "min_pane_seen": _opt_min([s["min_pane_seen"] for s in snaps]),
        "max_pane_seen": _opt_max([s["max_pane_seen"] for s in snaps]),
        "refire": sorted(set().union(*[set(s["refire"]) for s in snaps])),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "records_dropped_full": sum(
            int(s.get("records_dropped_full", 0)) for s in snaps),
    }


def _merge_lsm_spill(parts, g: _Geo) -> Optional[Dict[str, Any]]:
    """Fuse the old processes' lsm spill tiers into one pure-delta lsm
    snapshot for the new range (state/lsm.py merge_rescale_spill): run
    rows filter by their stored key-group column, delta keys re-hash —
    the disk tier rescales where the RAM spill ledger cannot."""
    if not parts:
        return None
    from flink_tpu.state.lsm import merge_rescale_spill

    try:
        return merge_rescale_spill(parts, num_shards=g.num_shards,
                                   shard_lo=g.new_lo, shard_hi=g.new_hi)
    except (ValueError, OSError) as e:
        raise RescaleError(f"lsm spill merge failed: {e}") from e


def _merge_session(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    cols_list = [s["columns"] for s in snaps]
    names = list(cols_list[0])
    cols = {c: np.concatenate([np.asarray(cl[c]) for cl in cols_list])
            for c in names}
    sh = hash_shards(cols["key"], g.num_shards)
    m = (sh >= g.new_lo) & (sh < g.new_hi)
    cols = {c: v[m] for c, v in cols.items()}
    order = np.lexsort((cols["start"], cols["key"]))  # _merged_columns order
    return {
        "watermark": min(s["watermark"] for s in snaps),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "columns": {c: v[order] for c, v in cols.items()},
    }


def _merge_states(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    """KeyedProcessOperator named-state columns. State registers lazily
    on first use, so a name may exist on only SOME old processes — the
    missing spans fill with the descriptor's defaults."""
    names: Dict[str, tuple] = {}
    for s in snaps:
        for n, (cls_name, desc, _) in s.items():
            names.setdefault(n, (cls_name, desc))
    out = {}
    for n, (cls_name, desc) in names.items():
        cols, stamps = [], []
        any_stamp = any(n in s and s[n][2]["stamp"] is not None
                        for s in snaps)
        for s in snaps:
            if n in s:
                cols.append(np.asarray(s[n][2]["col"]))
                st = s[n][2]["stamp"]
                stamps.append(None if st is None else np.asarray(st))
            else:
                if cls_name == "ValueStateVector":
                    cols.append(np.full(g.R, desc.default, desc.dtype))
                else:
                    cols.append(np.empty(g.R, object))
                stamps.append(None)
        col = _splice_slots(cols, g)
        stamp = None
        if any_stamp:
            stamp = _splice_slots(
                [st if st is not None else np.zeros(g.R, np.int64)
                 for st in stamps], g)
        out[n] = (cls_name, desc, {"col": col, "stamp": stamp})
    return out


def _merge_process(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    return {
        "kind": "process",
        "directory": _merge_directory(
            [s["directory"] for s in snaps], g,
            src_ranged=False, tgt_ranged=False),
        # timers self-fire on the watermark — filtering them to the new
        # range is what keeps two new processes from both firing a key
        "timers": _merge_timers([s["timers"] for s in snaps], g),
        "proc_timers": _merge_timers(
            [s.get("proc_timers") or
             {"slots": np.zeros(0, np.int64), "ts": np.zeros(0, np.int64)}
             for s in snaps], g),
        "watermark": min(s["watermark"] for s in snaps),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "records_dropped_full": sum(
            int(s["records_dropped_full"]) for s in snaps),
        "states": _merge_states([s["states"] for s in snaps], g),
    }


def _merge_cep(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    def splice(field):
        arrs = [s[field] for s in snaps]
        if arrs[0] is None:
            return None
        return _splice_slots(arrs, g)

    return {
        "kind": "cep",
        "directory": _merge_directory(
            [s["directory"] for s in snaps], g,
            src_ranged=False, tgt_ranged=False),
        "stage": splice("stage"),
        "stage_ts": splice("stage_ts"),
        "loop_cnt": splice("loop_cnt"),
        "loop_last": splice("loop_last"),
        "last_ts": splice("last_ts"),
        "p_stage": splice("p_stage"),
        "p_ts": splice("p_ts"),
        "watermark": min(s["watermark"] for s in snaps),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "records_dropped_full": sum(
            int(s["records_dropped_full"]) for s in snaps),
    }


_COUNT_FILLS = (0.0, -np.inf, np.inf, 0, 0)


def _merge_count_window(snaps: Sequence[Dict[str, Any]],
                        g: _Geo) -> Dict[str, Any]:
    arrays = []
    for i, fill in enumerate(_COUNT_FILLS):
        # (R + 1, ...): body is slot-indexed, row R is the dump row
        bodies = [np.asarray(s["arrays"][i])[:g.R] for s in snaps]
        body = _splice_slots(bodies, g)
        dump = np.full((1,) + body.shape[1:], fill, dtype=body.dtype)
        arrays.append(np.concatenate([body, dump]))
    return {
        "kind": "count_window",
        "arrays": tuple(arrays),
        "directory": _merge_directory(
            [s["directory"] for s in snaps], g,
            src_ranged=False, tgt_ranged=False),
        "watermark": min(s["watermark"] for s in snaps),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "records_dropped_full": sum(
            int(s.get("records_dropped_full", 0)) for s in snaps),
    }


def _merge_global_agg(snaps: Sequence[Dict[str, Any]],
                      g: _Geo) -> Dict[str, Any]:
    out = {
        "kind": "global_agg",
        "directory": _merge_directory(
            [s["directory"] for s in snaps], g,
            src_ranged=False, tgt_ranged=False),
        "counts": _splice_slots([s["counts"] for s in snaps], g),
        "sums": _splice_slots([s["sums"] for s in snaps], g),
        "maxs": _splice_slots([s["maxs"] for s in snaps], g),
        "mins": _splice_slots([s["mins"] for s in snaps], g),
        "watermark": min(s["watermark"] for s in snaps),
        "records_dropped_full": sum(
            int(s.get("records_dropped_full", 0)) for s in snaps),
    }
    # retract mode adds last-emitted bookkeeping; absent on append-mode
    # snapshots (and pre-retract checkpoints), so splice conditionally
    for field in ("prev_counts", "prev_sums", "prev_maxs", "prev_mins",
                  "emitted"):
        if field in snaps[0]:
            out[field] = _splice_slots([s[field] for s in snaps], g)
    return out


def _merge_evicting(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    keep = []
    for s in snaps:
        for b in s["bufs"]:
            sh = int(hash_shards(
                np.asarray([b["key"]], np.int64), g.num_shards)[0])
            if g.new_lo <= sh < g.new_hi:
                keep.append(b)
    return {
        "kind": "evicting_window",
        "watermark": min(s["watermark"] for s in snaps),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "bufs": keep,
    }


def _merge_side_buffer(snaps: Sequence[Dict[str, Any]],
                       g: _Geo) -> Dict[str, Any]:
    """Pairs-join _SideBuffer: ragged (pane, key, cols) rows. Each key
    lives on exactly ONE old process, so concatenation preserves per-key
    insertion order (the join's stable argsort keeps it)."""
    panes = np.concatenate([np.asarray(s["panes"]) for s in snaps])
    keys = np.concatenate([np.asarray(s["keys"], np.int64) for s in snaps])
    names = list(snaps[0]["cols"])
    cols = {f: np.concatenate([np.asarray(s["cols"][f]) for s in snaps])
            for f in names}
    sh = hash_shards(keys, g.num_shards)
    m = (sh >= g.new_lo) & (sh < g.new_hi)
    return {"panes": panes[m], "keys": keys[m],
            "cols": {f: v[m] for f, v in cols.items()}}


def _merge_join(snaps: Sequence[Dict[str, Any]], g: _Geo) -> Dict[str, Any]:
    mode = snaps[0].get("mode", "aggregate")
    if mode == "aggregate":
        return {
            "mode": "aggregate",
            # aggregate-mode sides are full-width WindowOperators (no
            # mesh, no shard range — see WindowJoinOperator.__init__)
            "left": _merge_window([s["left"] for s in snaps], g,
                                  tgt_ranged=False),
            "right": _merge_window([s["right"] for s in snaps], g,
                                   tgt_ranged=False),
        }
    out = {
        "mode": "pairs",
        "left": _merge_side_buffer([s["left"] for s in snaps], g),
        "right": _merge_side_buffer([s["right"] for s in snaps], g),
        # HostPaneControl fields ride the top level (ctl.snapshot())
        "watermark": min(s["watermark"] for s in snaps),
        "late_records": sum(int(s["late_records"]) for s in snaps),
        "refire": sorted(set().union(*[set(s["refire"]) for s in snaps])),
        "cleared_below": min(s["cleared_below"] for s in snaps),
        "fired_below_end": _opt_max([s["fired_below_end"] for s in snaps]),
        "min_pane_seen": _opt_min([s["min_pane_seen"] for s in snaps]),
        "max_pane_seen": _opt_max([s["max_pane_seen"] for s in snaps]),
    }
    return out


def _merge_operator(kind: str, snaps: Sequence[Dict[str, Any]], g: _Geo,
                    new_nproc: int) -> Any:
    if kind == "window":
        # the factory hands shard_range to the window op only when the
        # job runs multi-process — the target layout follows suit
        return _merge_window(snaps, g, tgt_ranged=new_nproc > 1)
    if kind == "session":
        return _merge_session(snaps, g)
    if kind == "process":
        return _merge_process(snaps, g)
    if kind == "cep":
        return _merge_cep(snaps, g)
    if kind == "count_window":
        return _merge_count_window(snaps, g)
    if kind == "global_agg":
        return _merge_global_agg(snaps, g)
    if kind == "evicting_window":
        return _merge_evicting(snaps, g)
    if kind == "join":
        return _merge_join(snaps, g)
    raise RescaleError(
        f"no repartition rule for keyed operator kind {kind!r} — "
        "teach checkpoint/repartition.py its snapshot layout before "
        "rescaling jobs that use it")


# keyless operators whose snapshots carry no shard-partitioned state:
# every old process holds an equivalent (or process-local) copy; the
# merged payload takes the min-watermark holder's snapshot verbatim
_KEYLESS_KINDS = frozenset({"window_all", "async_io", "broadcast_connect"})


def merge_payloads(payloads: Sequence[Dict[str, Any]], *, new_pid: int,
                   new_nproc: int, num_shards: int, slots_per_shard: int,
                   op_kinds: Dict[Any, str]) -> Dict[str, Any]:
    """Fuse one savepoint per OLD process (old-pid order) into a single
    restorable payload for NEW process ``new_pid`` of ``new_nproc``.

    ``op_kinds`` maps operator node id -> plan kind (the merge rule
    dispatch). Driver-level state merges too: split positions come from
    each split's old owner (owner of split s = s % N_old, the strided
    enumeration contract), watermark state takes the fleet min, and
    staged 2PC sink epochs are dropped — the savepoint committed them
    synchronously before the set was complete."""
    if not payloads:
        raise RescaleError("empty savepoint set")
    n_old = len(payloads)
    for o, p in enumerate(payloads):
        ident = p.get("rescale") or {}
        if ident and int(ident.get("nproc", n_old)) != n_old:
            raise RescaleError(
                f"savepoint set has {n_old} payloads but payload {o} was "
                f"written by a {ident['nproc']}-process fleet")
        if ident and int(ident.get("pid", o)) != o:
            raise RescaleError(
                f"savepoint set out of order: payload {o} carries "
                f"pid {ident['pid']} (sort by -p<pid>/ before merging)")
    g = _Geo(n_old, new_pid, new_nproc, num_shards, slots_per_shard)

    ops: Dict[Any, Any] = {}
    for nid, kind in op_kinds.items():
        snaps = [p["operators"][nid] for p in payloads
                 if nid in p["operators"]]
        if not snaps:
            continue
        if len(snaps) != n_old:
            raise RescaleError(
                f"operator {nid!r} missing from part of the savepoint "
                f"set ({len(snaps)}/{n_old} payloads)")
        if kind in _KEYLESS_KINDS:
            ops[nid] = snaps[0]
        else:
            ops[nid] = _merge_operator(kind, snaps, g, new_nproc)

    # driver plane: positions/wm per split from its old OWNER (strided
    # split enumeration: owner of split s at N processes is s % N)
    positions: Dict[Any, Dict[int, int]] = {}
    wm_gens: Dict[Any, list] = {}
    for sid, pos0 in payloads[0]["sources"].items():
        merged_pos: Dict[int, int] = {}
        for i in pos0:
            owner = int(i) % n_old
            merged_pos[i] = payloads[owner]["sources"][sid][i]
        positions[sid] = merged_pos
        gens0 = payloads[0].get("wm_gens", {}).get(sid, [])
        wm_gens[sid] = [payloads[int(i) % n_old]["wm_gens"][sid][int(i)]
                        for i in range(len(gens0))]

    max_ts = {}
    out_wm = {}
    for sid in payloads[0].get("max_ts", {}):
        max_ts[sid] = max(p["max_ts"][sid] for p in payloads)
    for sid in payloads[0].get("out_wm", {}):
        out_wm[sid] = min(p["out_wm"][sid] for p in payloads)

    metrics: Dict[str, Any] = {}
    for p in payloads:
        for k, v in p.get("metrics", {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metrics[k] = metrics.get(k, 0) + v
            else:
                metrics.setdefault(k, v)

    return {
        "sources": positions,
        "sub_factors": dict(payloads[0].get("sub_factors", {})),
        "wm_gens": wm_gens,
        "max_ts": max_ts,
        "out_wm": out_wm,
        "operators": ops,
        "op_versions": dict(payloads[0].get("op_versions", {})),
        # round-robin/shuffle counters reset on rescale (keyed routing
        # is stateless hash — unaffected)
        "partitioners": {},
        # staged 2PC epochs were committed by the savepoint itself; an
        # uncommitted epoch cannot survive into the set (checkpoint_now
        # is synchronous) — nothing to re-commit here
        "sinks": {},
        "metrics": metrics,
        "checkpoint_id": max(
            int(p.get("checkpoint_id", 0)) for p in payloads),
        # the merged payload restores THIS identity; a later restore of
        # the same file re-checks it (driver _run_loop)
        "rescale": {"nproc": new_nproc, "pid": new_pid,
                    "num_shards": num_shards,
                    "shard_range": [g.new_lo, g.new_hi]},
    }
