"""Checkpoint coordination — trigger, collect, complete, restore.

ref: runtime/checkpoint/CheckpointCoordinator.java (triggerCheckpoint /
receiveAcknowledgeMessage / restoreLatestCheckpointedStateToAll) and the
task-side SubtaskCheckpointCoordinatorImpl.checkpointState.

TPU-first simplification (SURVEY §6.4): a microbatch step boundary IS a
global barrier — no in-band barrier alignment, no channel state. A
checkpoint is: freeze (source positions, per-operator state snapshots,
watermarks), upload, mark complete, notify sinks to commit their staged
epoch. Exactly-once = replayable sources (positions) + state rollback +
transactional sinks.

Asynchrony (the HeapSnapshotStrategy async-part analogue, SURVEY §6.4):
the in-loop part of a checkpoint is only the FREEZE — sink staging plus
per-operator snapshots whose device leaves are dispatched on-device
clones (no device→host transfer, no serialization). The expensive part
— fetching the clones to host, pickling, writing, fsync — runs on a
background thread via ``trigger_async``; the 2PC commit happens only
after the manifest is durable, applied back on the loop thread when it
polls ``PendingCheckpoint`` (the asynchronous notifyCheckpointComplete
of the reference). Ingest never waits on storage.
"""
from __future__ import annotations

import dataclasses
import pickle
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from flink_tpu.checkpoint.storage import (
    CheckpointHandle, FsCheckpointStorage, ReusedOpState)


def materialize_snapshot(obj: Any) -> Any:
    """Recursively fetch device leaves of a frozen snapshot to host.
    Runs on the BACKGROUND thread — the freeze left cloned jax arrays in
    the tree precisely so this transfer leaves the hot loop."""
    if isinstance(obj, jax.Array):
        return jax.device_get(obj)
    if isinstance(obj, dict):
        return {k: materialize_snapshot(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(materialize_snapshot(v) for v in obj)
    if isinstance(obj, list):
        return [materialize_snapshot(v) for v in obj]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.replace(obj, **{
            f.name: materialize_snapshot(getattr(obj, f.name))
            for f in dataclasses.fields(obj)})
    return obj


class PendingCheckpoint:
    """An in-flight async checkpoint: freeze done, persistence running.
    ``complete()`` (loop thread) blocks if needed, then commits the 2PC
    epoch and records stats; ``abandon()`` drops it without committing."""

    def __init__(self, coordinator: "CheckpointCoordinator", cid: int,
                 future: "Future[CheckpointHandle]",
                 commit_fns: List[Callable[[int], None]],
                 t0: float,
                 abort_fns: Optional[List[Callable[[int], None]]] = None,
                 ) -> None:
        self.coordinator = coordinator
        self.checkpoint_id = cid
        self.future = future
        self._commit_fns = commit_fns
        self._abort_fns = list(abort_fns or [])
        self._t0 = t0
        self._end_cell: List[Optional[float]] = [None]

    @property
    def persist_end(self) -> Optional[float]:
        return self._end_cell[0]

    def done(self) -> bool:
        return self.future.done()

    def complete(self) -> CheckpointHandle:
        handle = self.future.result()  # re-raises persistence errors
        for c in self._commit_fns:
            c(self.checkpoint_id)
        # size and persist duration were computed on the BACKGROUND
        # thread (handle fields); the loop-thread commit does no storage
        # I/O — that is the whole point of the async split
        self.coordinator.stats.append(CheckpointStats(
            self.checkpoint_id, int(self._t0 * 1000),
            (self.persist_end - self._t0) * 1000
            if self.persist_end else (time.time() - self._t0) * 1000,
            max(handle.size_bytes, 0)))
        return handle

    def abandon(self) -> None:
        """Drop the in-flight checkpoint without committing, and
        deliver ABORT notifications to the 2PC sinks (ref:
        CheckpointCoordinator.sendAbortedMessages →
        notifyCheckpointAborted): the epoch staged at this barrier
        replays from the previous checkpoint's source positions, so
        its staged transaction may be rolled back durably. Runs on the
        attempt's failure path — a broken abort hook must not mask the
        original failure, so errors are recorded, not raised."""
        self.future.cancel()
        from flink_tpu.obs.tracing import tracer

        for a in self._abort_fns:
            try:
                a(self.checkpoint_id)
            except Exception as e:  # noqa: BLE001 — cleanup best-effort
                with tracer.span("checkpoint.abort-notify-failed",
                                 checkpoint_id=self.checkpoint_id,
                                 error=f"{type(e).__name__}: {e}"):
                    pass


@dataclasses.dataclass
class CheckpointStats:
    """ref: CheckpointStatsTracker — per-checkpoint visibility."""

    checkpoint_id: int
    trigger_ts_ms: int
    duration_ms: float
    size_bytes: int


class CheckpointCoordinator:
    def __init__(self, storage: FsCheckpointStorage) -> None:
        self.storage = storage
        self._next_id = 1
        self.stats: List[CheckpointStats] = []

    def trigger(
        self,
        snapshot_fn: Callable[[], Dict[str, Any]],
        commit_fns: List[Callable[[int], None]],
        prepare_fns: List[Callable[[int], None]],
        savepoint: bool = False,
        executor=None,
        abort_fns: Optional[List[Callable[[int], None]]] = None,
    ) -> CheckpointHandle:
        """One full SYNCHRONOUS checkpoint cycle — freeze, persist,
        commit, in the caller's thread (savepoints, final checkpoints,
        tests). The interval path uses ``trigger_async``."""
        pending = self.trigger_async(
            snapshot_fn, commit_fns, prepare_fns,
            executor=executor, savepoint=savepoint, abort_fns=abort_fns)
        return pending.complete()

    def trigger_async(
        self,
        snapshot_fn: Callable[[], Dict[str, Any]],
        commit_fns: List[Callable[[int], None]],
        prepare_fns: List[Callable[[int], None]],
        executor=None,
        savepoint: bool = False,
        abort_fns: Optional[List[Callable[[int], None]]] = None,
    ) -> PendingCheckpoint:
        """Freeze in the caller's thread, persist in the background:
        1. (loop) sinks stage their epoch (prepareCommit)
        2. (loop) freeze: snapshot tree with on-device cloned leaves
        3. (bg)   fetch leaves, serialize, write, manifest last
        4. (loop, via PendingCheckpoint.complete) sinks commit (2PC)
        """
        from flink_tpu.obs.tracing import tracer

        cid = self._next_id
        self._next_id += 1
        t0 = time.time()
        # checkpoint spans (ref: CheckpointStatsTracker reporting
        # checkpointing spans through the trace reporters, SURVEY §6.1):
        # 'checkpoint.freeze' = the sync part stalling the loop,
        # 'checkpoint.persist' = the async upload — the two durations
        # that matter are separate spans, not one blended number
        with tracer.span("checkpoint.freeze", checkpoint_id=cid,
                         savepoint=savepoint):
            for p in prepare_fns:
                p(cid)
            payload = snapshot_fn()
        payload["checkpoint_id"] = cid
        end_cell: List[Optional[float]] = [None]

        def persist() -> CheckpointHandle:
            psp = tracer.span("checkpoint.persist", checkpoint_id=cid)
            try:
                with psp:
                    # the async-upload fault seam: a raise here fails the
                    # persistence future exactly like a dead background
                    # uploader — the loop thread sees it at complete()
                    from flink_tpu import faults

                    faults.fire("checkpoint.upload", exc=OSError,
                                checkpoint_id=cid)
                    from flink_tpu.fs import enospc_retry

                    mat = materialize_snapshot(payload)
                    ops = mat.pop("operators", None)
                    if ops is None:
                        # whole-save ENOSPC retry (storage.enospc-
                        # policy=retry): each attempt writes a FRESH
                        # unique tmp dir, so a failed attempt leaves
                        # only sweepable debris — retention freeing
                        # space between attempts is the degrade path
                        h = enospc_retry(lambda: self.storage.save(
                            cid, mat, savepoint=savepoint))
                    else:
                        blobs: Dict[str, bytes] = {}
                        reuse: Dict[str, ReusedOpState] = {}
                        op_aux: Dict[str, Dict[str, str]] = {}
                        from flink_tpu.checkpoint import blobformat

                        for nid, snap in ops.items():
                            if isinstance(snap, ReusedOpState):
                                reuse[str(nid)] = snap
                            else:
                                # changelog plane (lsm runs): the files
                                # named here ride as hardlinks, never
                                # through the serializer
                                if isinstance(snap, dict):
                                    aux = snap.pop("__aux_files__", None)
                                    if aux:
                                        op_aux[str(nid)] = aux
                                # self-describing v3 blob, not pickle
                                # (schema evolution; SURVEY §3.1)
                                blobs[str(nid)] = blobformat.encode(snap)
                        h = enospc_retry(lambda: self.storage.save_v2(
                            cid, mat, blobs, reuse, savepoint=savepoint,
                            op_aux=op_aux))
                    psp.set("bytes", getattr(h, "size_bytes", None))
                    return h
            finally:
                end_cell[0] = time.time()

        if executor is None:
            fut: Future = Future()
            try:
                fut.set_result(persist())
            except BaseException as e:  # sync fallback mirrors a bg error
                fut.set_exception(e)
        else:
            fut = executor.submit(persist)
        pend = PendingCheckpoint(self, cid, fut, commit_fns, t0,
                                 abort_fns=abort_fns)
        pend._end_cell = end_cell
        return pend

    def restore_latest(self) -> Optional[Dict[str, Any]]:
        from flink_tpu.obs.tracing import tracer

        h = self.storage.latest()
        if h is None:
            return None
        with tracer.span("restore", path=getattr(h, "path", None)) as sp:
            payload = FsCheckpointStorage.load(h)
            sp.set("checkpoint_id", payload.get("checkpoint_id"))
        self.resume_numbering(payload)
        return payload

    def resume_numbering(self, payload: Dict[str, Any]) -> None:
        """Checkpoint ids must keep increasing across restores — id reuse
        would clobber retained checkpoints and replay 2PC epoch ids
        (ref: CheckpointIDCounter in HA services)."""
        self._next_id = max(self._next_id,
                            int(payload.get("checkpoint_id", 0)) + 1)
