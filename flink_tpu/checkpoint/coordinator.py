"""Checkpoint coordination — trigger, collect, complete, restore.

ref: runtime/checkpoint/CheckpointCoordinator.java (triggerCheckpoint /
receiveAcknowledgeMessage / restoreLatestCheckpointedStateToAll) and the
task-side SubtaskCheckpointCoordinatorImpl.checkpointState.

TPU-first simplification (SURVEY §6.4): a microbatch step boundary IS a
global barrier — no in-band barrier alignment, no channel state. A
checkpoint is: freeze (source positions, per-operator state snapshots,
watermarks), upload, mark complete, notify sinks to commit their staged
epoch. Exactly-once = replayable sources (positions) + state rollback +
transactional sinks.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from flink_tpu.checkpoint.storage import CheckpointHandle, FsCheckpointStorage


@dataclasses.dataclass
class CheckpointStats:
    """ref: CheckpointStatsTracker — per-checkpoint visibility."""

    checkpoint_id: int
    trigger_ts_ms: int
    duration_ms: float
    size_bytes: int


class CheckpointCoordinator:
    def __init__(self, storage: FsCheckpointStorage) -> None:
        self.storage = storage
        self._next_id = 1
        self.stats: List[CheckpointStats] = []

    def trigger(
        self,
        snapshot_fn: Callable[[], Dict[str, Any]],
        commit_fns: List[Callable[[int], None]],
        prepare_fns: List[Callable[[int], None]],
        savepoint: bool = False,
    ) -> CheckpointHandle:
        """One full checkpoint cycle (synchronous local form; the
        coordinator process does the same over RPC for multi-host):
        1. sinks stage their epoch (prepareCommit)
        2. collect state snapshot at the step boundary
        3. persist (manifest last)
        4. notify complete → sinks commit (2PC)
        """
        cid = self._next_id
        self._next_id += 1
        t0 = time.time()
        for p in prepare_fns:
            p(cid)
        payload = snapshot_fn()
        payload["checkpoint_id"] = cid
        handle = self.storage.save(cid, payload, savepoint=savepoint)
        for c in commit_fns:
            c(cid)
        import os

        size = 0
        for root, _, files in os.walk(handle.path):
            for fn in files:
                size += os.path.getsize(os.path.join(root, fn))
        self.stats.append(CheckpointStats(
            cid, int(t0 * 1000), (time.time() - t0) * 1000, size))
        return handle

    def restore_latest(self) -> Optional[Dict[str, Any]]:
        h = self.storage.latest()
        if h is None:
            return None
        payload = FsCheckpointStorage.load(h)
        self.resume_numbering(payload)
        return payload

    def resume_numbering(self, payload: Dict[str, Any]) -> None:
        """Checkpoint ids must keep increasing across restores — id reuse
        would clobber retained checkpoints and replay 2PC epoch ids
        (ref: CheckpointIDCounter in HA services)."""
        self._next_id = max(self._next_id,
                            int(payload.get("checkpoint_id", 0)) + 1)
