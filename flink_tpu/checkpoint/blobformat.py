"""Self-describing checkpoint blob format (format_version 3).

ref: the role of TypeSerializerSnapshot (flink-core/.../api/common/
typeutils/TypeSerializerSnapshot.java) — snapshots must be readable
across code changes and from non-JVM tooling. The v1/v2 payloads were
raw pickle: moving a dataclass field between save and restore, or
reading a savepoint from anything but this exact Python codebase,
broke. v3 is:

    [8B magic b"FTCKPT3\\n"][u32 header_len][header JSON][array section]

The header's ``tree`` mirrors the payload structure as plain JSON with
tagged placeholders; numpy/jax array leaves live in the array section
(raw C-order bytes, 64-byte-aligned offsets, dtype+shape in the
header's ``arrays`` table). Schema evolution = dict-field evolution:
readers use .get with defaults, unknown fields are preserved, and any
tool that can parse JSON + memmap raw arrays can read a savepoint.

Tags (JSON objects with one reserved key):
    {"__nd__": i}                     array-section index i
    {"__tup__": [...]}                tuple
    {"__kdict__": [[k, v], ...]}      dict with non-string keys
    {"__np__": [dtype, value]}        numpy scalar
    {"__bytes__": base64}             bytes
    {"__strs__": [shape, [str, ...]]} all-string object-dtype array
                                      (text columns; no pickle needed)
    {"__panestate__": {...}}          state.keyed.PaneState
    {"__pickle__": base64}            escape hatch for foreign objects
                                      (framework snapshots produce none
                                      — tests assert the counter stays
                                      zero; user-defined operator state
                                      may still need it)
"""
from __future__ import annotations

import base64
import json
import struct
from typing import Any, Dict, List, Tuple

import numpy as np

MAGIC = b"FTCKPT3\n"
_ALIGN = 64


class _Encoder:
    def __init__(self) -> None:
        self.arrays: List[np.ndarray] = []
        self.pickle_escapes = 0

    def enc(self, v: Any) -> Any:
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, bytes):
            return {"__bytes__": base64.b64encode(v).decode()}
        if isinstance(v, np.generic):
            return {"__np__": [str(v.dtype), v.item()]}
        if isinstance(v, np.ndarray):
            # object-dtype arrays have no raw-byte form — np.frombuffer
            # can't decode them, so the array section would produce an
            # unrestorable checkpoint. ALL-STRING object arrays (the
            # common case: text columns from socket/file sources) get a
            # native JSON tag, so they stay readable by foreign tooling
            # AND cross the pickle-rejecting DCN decoder
            # (allow_pickle=False); anything else still takes the
            # counted pickle escape hatch.
            if v.dtype.hasobject:
                flat = v.ravel()
                if all(isinstance(x, str) for x in flat):
                    return {"__strs__": [list(v.shape), list(flat)]}
                import pickle

                self.pickle_escapes += 1
                return {"__pickle__": base64.b64encode(pickle.dumps(
                    v, protocol=pickle.HIGHEST_PROTOCOL)).decode()}
            # ascontiguousarray promotes 0-d to (1,) — restore the shape
            self.arrays.append(np.ascontiguousarray(v).reshape(v.shape))
            return {"__nd__": len(self.arrays) - 1}
        # jax arrays (avoid importing jax here for tool-side reuse)
        if type(v).__module__.startswith("jax") and hasattr(v, "dtype"):
            self.arrays.append(np.ascontiguousarray(np.asarray(v)))
            return {"__nd__": len(self.arrays) - 1}
        if isinstance(v, tuple):
            return {"__tup__": [self.enc(x) for x in v]}
        if isinstance(v, list):
            return [self.enc(x) for x in v]
        if isinstance(v, dict):
            if all(isinstance(k, str) and not k.startswith("__") for k in v):
                return {k: self.enc(x) for k, x in v.items()}
            return {"__kdict__": [[self.enc(k), self.enc(x)]
                                  for k, x in v.items()]}
        pane = _as_panestate_fields(v)
        if pane is not None:
            return {"__panestate__": {k: self.enc(x)
                                      for k, x in pane.items()}}
        import pickle

        self.pickle_escapes += 1
        return {"__pickle__": base64.b64encode(
            pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)).decode()}


def _as_panestate_fields(v: Any):
    from flink_tpu.state.keyed import PaneState

    if isinstance(v, PaneState):
        return {"sums": v.sums, "maxs": v.maxs, "mins": v.mins,
                "counts": v.counts}
    return None


def encode(payload: Any) -> bytes:
    """Payload tree → self-describing v3 bytes."""
    e = _Encoder()
    tree = e.enc(payload)
    offsets = []
    pos = 0
    for a in e.arrays:
        pos = (pos + _ALIGN - 1) // _ALIGN * _ALIGN
        offsets.append(pos)
        pos += a.nbytes
    header = json.dumps({
        "tree": tree,
        "arrays": [{"dtype": str(a.dtype), "shape": list(a.shape),
                    "offset": off, "nbytes": a.nbytes}
                   for a, off in zip(e.arrays, offsets)],
        "pickle_escapes": e.pickle_escapes,
    }).encode()
    out = bytearray()
    out += MAGIC
    out += struct.pack("<I", len(header))
    out += header
    base = len(out)
    out += b"\0" * (pos if e.arrays else 0)
    for a, off in zip(e.arrays, offsets):
        out[base + off:base + off + a.nbytes] = a.tobytes()
    return bytes(out)


class _Decoder:
    def __init__(self, arrays: List[np.ndarray],
                 allow_pickle: bool = True) -> None:
        self.arrays = arrays
        self.allow_pickle = allow_pickle

    def dec(self, v: Any) -> Any:
        if isinstance(v, list):
            return [self.dec(x) for x in v]
        if not isinstance(v, dict):
            return v
        if "__nd__" in v:
            return self.arrays[v["__nd__"]]
        if "__tup__" in v:
            return tuple(self.dec(x) for x in v["__tup__"])
        if "__kdict__" in v:
            return {_key(self.dec(k)): self.dec(x)
                    for k, x in v["__kdict__"]}
        if "__np__" in v:
            dt, val = v["__np__"]
            return np.dtype(dt).type(val)
        if "__bytes__" in v:
            return base64.b64decode(v["__bytes__"])
        if "__panestate__" in v:
            from flink_tpu.state.keyed import PaneState

            f = {k: self.dec(x) for k, x in v["__panestate__"].items()}
            return PaneState(sums=f.get("sums"), maxs=f.get("maxs"),
                             mins=f.get("mins"), counts=f.get("counts"))
        if "__strs__" in v:
            shape, items = v["__strs__"]
            a = np.empty(len(items), dtype=object)
            a[:] = items
            return a.reshape(shape)
        if "__pickle__" in v:
            if not self.allow_pickle:
                # network-facing decoders (the DCN exchange) must never
                # unpickle: an attacker-controlled __pickle__ tag is
                # arbitrary code execution on load
                raise ValueError(
                    "__pickle__ escape rejected (allow_pickle=False): "
                    "payload carries a foreign object where only "
                    "framework-built arrays are expected")
            import pickle

            return pickle.loads(base64.b64decode(v["__pickle__"]))
        return {k: self.dec(x) for k, x in v.items()}


def _key(k: Any) -> Any:
    # dict keys must stay hashable after decode; lists decode from JSON
    # arrays, so a tuple key round-trips via __tup__ already
    return k


def read_header(raw: bytes) -> Tuple[Dict[str, Any], int]:
    """Parse just the JSON header without touching the array section.
    Returns (header, array_section_base_offset)."""
    if len(raw) < len(MAGIC) + 4 or raw[:len(MAGIC)] != MAGIC:
        raise ValueError("not a FTCKPT3 blob (bad magic)")
    hstart = len(MAGIC) + 4
    hlen = struct.unpack("<I", raw[len(MAGIC):hstart])[0]
    return json.loads(raw[hstart:hstart + hlen].decode()), hstart + hlen


def decode(raw: bytes, allow_pickle: bool = True) -> Any:
    """v3 bytes → payload tree (arrays are read-only views when the
    input buffer allows zero-copy). ``allow_pickle=False`` rejects the
    ``__pickle__`` escape — required for any decoder fed from the
    network (see exchange/dcn.py)."""
    header, base = read_header(raw)
    arrays: List[np.ndarray] = []
    for spec in header["arrays"]:
        off = base + spec["offset"]
        a = np.frombuffer(raw, dtype=np.dtype(spec["dtype"]),
                          count=int(np.prod(spec["shape"], dtype=np.int64))
                          if spec["shape"] else 1,
                          offset=off).reshape(spec["shape"])
        arrays.append(a)
    return _Decoder(arrays, allow_pickle=allow_pickle).dec(header["tree"])


def is_v3(raw: bytes) -> bool:
    return raw[:len(MAGIC)] == MAGIC
