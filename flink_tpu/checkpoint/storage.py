"""Checkpoint storage — durable snapshot layout and retention.

ref: runtime/state/CheckpointStorage + filesystem layout of
FsCheckpointStorage (state.checkpoints.dir/<job>/chk-<n>/...) and
CompletedCheckpointStore retention (state.checkpoints.num-retained).

Layout here:
    <root>/<job_id>/chk-<n>/state.pkl      operator + source snapshots
    <root>/<job_id>/chk-<n>/MANIFEST.json  metadata; written LAST —
                                           a checkpoint without a
                                           manifest is incomplete and
                                           ignored/garbage-collected
Savepoints are the same format under <root>/<job_id>/savepoint-<n>/
(ref: SavepointType — manually triggered, never auto-retired).

Format note: the round-1 payload codec is pickle+numpy; a versioned
binary format (the TypeSerializerSnapshot schema-evolution analogue)
replaces it when the C++ codec lands.
"""
from __future__ import annotations

import dataclasses
import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class CheckpointHandle:
    checkpoint_id: int
    path: str
    timestamp_ms: int
    is_savepoint: bool = False


class FsCheckpointStorage:
    def __init__(self, root: str, job_id: str, retained: int = 3) -> None:
        self.root = root
        self.job_id = job_id
        self.retained = max(1, retained)
        self.job_dir = os.path.join(root, job_id)
        os.makedirs(self.job_dir, exist_ok=True)

    def _dir(self, checkpoint_id: int, savepoint: bool) -> str:
        prefix = "savepoint" if savepoint else "chk"
        return os.path.join(self.job_dir, f"{prefix}-{checkpoint_id}")

    def save(self, checkpoint_id: int, payload: Dict[str, Any],
             savepoint: bool = False) -> CheckpointHandle:
        """Write snapshot; manifest lands last so readers only ever see
        complete checkpoints (the atomic-rename pattern of
        FsCompletedCheckpointStorageLocation)."""
        d = self._dir(checkpoint_id, savepoint)
        tmp = d + ".inprogress"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, "state.pkl"), "wb") as f:
            pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        ts = int(time.time() * 1000)
        with open(os.path.join(tmp, "MANIFEST.json"), "w", encoding="utf-8") as f:
            json.dump({
                "checkpoint_id": checkpoint_id,
                "timestamp_ms": ts,
                "job_id": self.job_id,
                "savepoint": savepoint,
                "format_version": 1,
            }, f)
        if os.path.exists(d):
            shutil.rmtree(d)
        os.rename(tmp, d)
        if not savepoint:
            self._retire_old()
        return CheckpointHandle(checkpoint_id, d, ts, savepoint)

    def list_complete(self) -> List[CheckpointHandle]:
        out = []
        for name in os.listdir(self.job_dir):
            d = os.path.join(self.job_dir, name)
            mf = os.path.join(d, "MANIFEST.json")
            if not os.path.isfile(mf):
                continue
            try:
                with open(mf, "r", encoding="utf-8") as f:
                    m = json.load(f)
                out.append(CheckpointHandle(
                    m["checkpoint_id"], d, m["timestamp_ms"],
                    m.get("savepoint", False)))
            except (json.JSONDecodeError, KeyError):
                continue
        return sorted(out, key=lambda h: h.checkpoint_id)

    def latest(self) -> Optional[CheckpointHandle]:
        hs = [h for h in self.list_complete() if not h.is_savepoint]
        return hs[-1] if hs else None

    @staticmethod
    def load(handle_or_path) -> Dict[str, Any]:
        path = getattr(handle_or_path, "path", handle_or_path)
        with open(os.path.join(path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def _retire_old(self) -> None:
        hs = [h for h in self.list_complete() if not h.is_savepoint]
        for h in hs[: -self.retained]:
            shutil.rmtree(h.path, ignore_errors=True)
        # sweep orphaned in-progress dirs
        for name in os.listdir(self.job_dir):
            if name.endswith(".inprogress"):
                shutil.rmtree(os.path.join(self.job_dir, name),
                              ignore_errors=True)
