"""Checkpoint storage — durable snapshot layout and retention.

ref: runtime/state/CheckpointStorage + filesystem layout of
FsCheckpointStorage (state.checkpoints.dir/<job>/chk-<n>/...) and
CompletedCheckpointStore retention (state.checkpoints.num-retained).

Layout here:
    <root>/<job_id>/chk-<n>/state.pkl      operator + source snapshots
    <root>/<job_id>/chk-<n>/MANIFEST.json  metadata; written LAST —
                                           a checkpoint without a
                                           manifest is incomplete and
                                           ignored/garbage-collected
Savepoints are the same format under <root>/<job_id>/savepoint-<n>/
(ref: SavepointType — manually triggered, never auto-retired).

Format v2 (incremental, the RocksDB shared-SST analogue): operator
state splits into per-operator blob files
    <chk>/meta.pkl            everything except operator state
    <chk>/op-<nid>.pkl        one operator's snapshot
    <chk>/MANIFEST.json       format_version 2 + per-op file+version map
An operator UNCHANGED since the base checkpoint (same state_version) is
not re-serialized: its blob is HARDLINKED from the base checkpoint's
file (falling back to copy), so an idle operator costs zero bytes of
new serialization and the link survives the base's retirement (inode
refcount — exactly how RocksDB incremental checkpoints share SSTs).

Format v3 keeps v2's directory layout (files named *.blob) but every
payload is the SELF-DESCRIBING binary format of
``checkpoint/blobformat.py`` (JSON-schema'd tree + raw array section)
instead of pickle — restorable across code changes and readable from
non-Python tooling (ref: TypeSerializerSnapshot's schema-evolution
role, SURVEY §3.1). v1/v2 pickle checkpoints remain loadable, and a v3
incremental checkpoint may hardlink op blobs written by a v2 base —
the loader dispatches per blob on the magic bytes.
"""
from __future__ import annotations

import dataclasses
import json
import os
import pickle
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu import faults
from flink_tpu.fs import FileSystem, get_filesystem, open_write_sync


@dataclasses.dataclass
class CheckpointHandle:
    checkpoint_id: int
    path: str
    timestamp_ms: int
    is_savepoint: bool = False
    # writer's leader epoch (manifest + dir-name qualified when > 0):
    # among same-id checkpoints the highest epoch is the live timeline
    epoch: int = 0
    size_bytes: int = -1  # filled by save/save_v2 (background thread)
    # op blob file names as written (save_v2 only): the incremental
    # reuse base must reference the ACTUAL names — a reused blob keeps
    # its lineage's extension across format upgrades
    op_files: Optional[Dict[str, str]] = None
    # per-op changelog aux files as written (save_v2 only): nid →
    # {logical name → file name under path}. The lsm state tier's
    # sealed runs ride checkpoints as hardlinks of immutable files;
    # the next checkpoint's reuse base links THESE, not the store's
    # live files, so aux survives the base's retirement (inode
    # refcount, same rule as op blob reuse).
    op_aux: Optional[Dict[str, Dict[str, str]]] = None


@dataclasses.dataclass
class ReusedOpState:
    """Marker in a snapshot's operators map: this operator's state is
    unchanged since the base checkpoint — reuse (hardlink) its blob
    instead of re-serializing. ``file`` is the absolute path of the base
    checkpoint's op blob; ``version`` the operator state_version it
    captured; ``aux`` the base's changelog aux files (logical name →
    absolute path) to re-link alongside the blob."""

    file: str
    version: int
    aux: Optional[Dict[str, str]] = None


class StaleCheckpointWriter(RuntimeError):
    """A deposed leader's writer tried to persist after a successor
    (higher epoch) already wrote — the write was fenced off."""


class FsCheckpointStorage:
    """All storage I/O goes through the FileSystem seam (flink_tpu.fs)
    — the checkpoint dir may live on any registered scheme (ref:
    FsCheckpointStorage resolving its path via FileSystem.get)."""

    def __init__(self, root: str, job_id: str, retained: int = 3,
                 compression: str = "none", epoch: int = 0) -> None:
        if compression not in ("none", "zlib"):
            raise ValueError(
                f"compression must be 'none' or 'zlib', got {compression!r}")
        self.root = root
        self.job_id = job_id
        self.retained = max(1, retained)
        self.compression = compression
        # leader-epoch fence (ref: the HA fencing token on RPCs, applied
        # to STORAGE writes): a deposed leader's in-flight persist must
        # not clobber a successor's checkpoints. Manifests record the
        # writer's epoch; any write aborts when the store already holds
        # a manifest from a HIGHER epoch. 0 = unfenced single-writer
        # (local driver without HA).
        self.epoch = epoch
        self.fs: FileSystem = get_filesystem(root)
        self.job_dir = os.path.join(root, job_id)
        self.fs.mkdirs(self.job_dir)

    def set_epoch(self, epoch: int) -> None:
        """Adopt the leader epoch granted by the election (coordinator
        HA); all subsequent writes carry and check it."""
        self.epoch = epoch

    def _check_fence(self) -> None:
        """Abort the write when ANY completed manifest carries a higher
        epoch — this writer has been deposed and its snapshot belongs
        to a dead timeline. Check-then-rename is not atomic; the lease
        interval bounds the race the same way it bounds RPC fencing."""
        if self.epoch == 0:
            return
        for h in self.list_complete():
            # handles carry the manifest's epoch — no second read
            if h.epoch > self.epoch:
                raise StaleCheckpointWriter(
                    f"checkpoint write fenced: store holds epoch "
                    f"{h.epoch} > this writer's {self.epoch} "
                    f"(deposed leader finishing late)")

    def _dir(self, checkpoint_id: int, savepoint: bool) -> str:
        prefix = "savepoint" if savepoint else "chk"
        # epoch-QUALIFIED final name under HA fencing: a deposed leader
        # renaming late lands on chk-<id>.e<oldEpoch>, a DIFFERENT path
        # from the successor's chk-<id>.e<newEpoch> — a stale writer can
        # never delete-and-replace a higher-epoch directory, closing the
        # check-then-rename window _check_fence alone leaves open.
        # latest()/list_complete pick the highest (id, epoch). Unfenced
        # local runs (epoch 0) keep the plain layout.
        if self.epoch and not savepoint:
            return os.path.join(
                self.job_dir, f"{prefix}-{checkpoint_id}.e{self.epoch}")
        return os.path.join(self.job_dir, f"{prefix}-{checkpoint_id}")

    def _tmp_dir(self, d: str) -> str:
        """Fresh UNIQUE in-progress dir: an abandoned background persist
        from a failed attempt may still be writing when a restarted
        attempt reuses the checkpoint id — distinct tmp dirs mean each
        writer produces a self-consistent directory, and the final
        atomic rename makes whole-dir last-writer-wins (never an
        interleaved mix of two attempts' files)."""
        import uuid

        tmp = f"{d}.inprogress.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        self.fs.mkdirs(tmp)
        return tmp

    def save(self, checkpoint_id: int, payload: Dict[str, Any],
             savepoint: bool = False) -> CheckpointHandle:
        """Write snapshot; manifest lands last so readers only ever see
        complete checkpoints (the atomic-rename pattern of
        FsCompletedCheckpointStorageLocation)."""
        from flink_tpu.checkpoint import blobformat

        d = self._dir(checkpoint_id, savepoint)
        tmp = self._tmp_dir(d)
        faults.fire("checkpoint.storage.stall", exc=OSError,
                    checkpoint_id=checkpoint_id)
        faults.fire("checkpoint.storage.write", exc=OSError,
                    checkpoint_id=checkpoint_id)
        # sync-on-close (the fs seam's durability barrier): every byte
        # of the checkpoint is on stable storage BEFORE the rename
        # publishes the directory — a power cut can lose the rename
        # (the checkpoint never existed; restore takes the previous
        # one) but can never publish torn content at the final name
        with open_write_sync(self.fs, os.path.join(tmp, "state.blob"),
                             sync=True) as f:
            f.write(self._pack(blobformat.encode(payload)))
        ts = int(time.time() * 1000)
        faults.fire("checkpoint.storage.fsync", exc=OSError,
                    checkpoint_id=checkpoint_id)
        with open_write_sync(self.fs, os.path.join(tmp, "MANIFEST.json"),
                             sync=True) as f:
            f.write(json.dumps({
                "checkpoint_id": checkpoint_id,
                "timestamp_ms": ts,
                "job_id": self.job_id,
                "savepoint": savepoint,
                "format_version": 3,
                "layout": "single",
                "compression": self.compression,
                "epoch": self.epoch,
            }).encode())
        try:
            self._check_fence()
        except StaleCheckpointWriter:
            try:
                self.fs.delete(tmp, recursive=True)
            except OSError:
                pass  # the FENCE is the signal — a failed tmp sweep
                # (now loud at the fs layer) must not replace it with a
                # generic persist error the retry machinery would chase
            raise
        # a rename fault here is the TORN-manifest scenario: the tmp dir
        # is fully written (manifest included) but never reaches its
        # final name — list_complete must keep ignoring it
        faults.fire("checkpoint.storage.rename", exc=OSError,
                    checkpoint_id=checkpoint_id)
        if self.fs.exists(d):
            self.fs.delete(d, recursive=True)
        self.fs.rename(tmp, d)
        # entry durability: the rename that published the checkpoint is
        # a directory mutation — fsync the job dir so 'save returned'
        # implies 'restore will find it' across a power cut
        self.fs.fsync(self.job_dir)
        if not savepoint:
            self._retire_old()
        return CheckpointHandle(checkpoint_id, d, ts, savepoint,
                                epoch=self.epoch, size_bytes=_dir_size(d))

    def save_v2(self, checkpoint_id: int, meta_payload: Dict[str, Any],
                op_blobs: Dict[str, bytes],
                op_reuse: Dict[str, "ReusedOpState"],
                savepoint: bool = False,
                op_aux: Optional[Dict[str, Dict[str, str]]] = None
                ) -> CheckpointHandle:
        """Incremental format: per-operator blob files; unchanged
        operators hardlink the base checkpoint's blob. ``op_aux`` (nid
        → {logical name → source path}) is the changelog plane: each
        named file — an lsm state tier's sealed, immutable, already-
        durable run — is hardlinked into the checkpoint instead of
        re-serialized, so checkpoint bytes scale with the write rate,
        not the state size (the flink-dstl role). Manifest lands last,
        exactly like v1."""
        from flink_tpu.checkpoint import blobformat

        d = self._dir(checkpoint_id, savepoint)
        tmp = self._tmp_dir(d)
        faults.fire("checkpoint.storage.stall", exc=OSError,
                    checkpoint_id=checkpoint_id)
        faults.fire("checkpoint.storage.write", exc=OSError,
                    checkpoint_id=checkpoint_id)
        versions: Dict[str, int] = {}
        op_files: Dict[str, str] = {}
        for nid, blob in op_blobs.items():
            fn = f"op-{nid}.blob"
            with open_write_sync(self.fs, os.path.join(tmp, fn),
                                 sync=True) as f:
                f.write(self._pack(blob))
            op_files[nid] = fn
            versions[nid] = meta_payload.get(
                "op_versions", {}).get(nid, -1)
        aux_links: Dict[str, Dict[str, str]] = {}

        def _link_aux(nid: str, mapping: Dict[str, str]) -> None:
            for logical, src in sorted(mapping.items()):
                fn = f"st-{nid}-{logical}"
                faults.fire("state.changelog.link", exc=OSError,
                            checkpoint_id=checkpoint_id, file=logical)
                self.fs.link_or_copy(src, os.path.join(tmp, fn))
                aux_links.setdefault(nid, {})[logical] = fn

        for nid, mapping in (op_aux or {}).items():
            _link_aux(nid, mapping)
        for nid, ref in op_reuse.items():
            # reuse keeps the BASE's file name (it may be a v2 .pkl
            # pickle blob — the loader dispatches on magic bytes)
            fn = f"op-{nid}{os.path.splitext(ref.file)[1]}"
            self.fs.link_or_copy(ref.file, os.path.join(tmp, fn))
            op_files[nid] = fn
            versions[nid] = ref.version
            if ref.aux:
                # an idle operator's changelog is its base's aux set,
                # re-linked so this checkpoint stays self-locating
                _link_aux(nid, ref.aux)
        if op_reuse or aux_links:
            # entry durability for the REUSE links: a hardlink is a
            # directory mutation the blobs' content fsyncs never cover
            # — without this dir barrier a power cut after save_v2
            # returned could keep the (durable) manifest while the
            # linked op-blob entry vanished, leaving an acked
            # checkpoint that cannot load (the crash explorer's
            # CheckpointTier.check_image guards this)
            self.fs.fsync(tmp)
        with open_write_sync(self.fs, os.path.join(tmp, "meta.blob"),
                             sync=True) as f:
            f.write(self._pack(blobformat.encode(meta_payload)))
        ts = int(time.time() * 1000)
        faults.fire("checkpoint.storage.fsync", exc=OSError,
                    checkpoint_id=checkpoint_id)
        with open_write_sync(self.fs, os.path.join(tmp, "MANIFEST.json"),
                             sync=True) as f:
            f.write(json.dumps({
                "checkpoint_id": checkpoint_id,
                "timestamp_ms": ts,
                "job_id": self.job_id,
                "savepoint": savepoint,
                "format_version": 3,
                "compression": self.compression,
                "ops": {nid: {"file": fn, "version": versions[nid]}
                        for nid, fn in op_files.items()},
                "aux": aux_links,
                "epoch": self.epoch,
            }).encode())
        try:
            self._check_fence()
        except StaleCheckpointWriter:
            try:
                self.fs.delete(tmp, recursive=True)
            except OSError:
                pass  # keep the fence signal (see save())
            raise
        faults.fire("checkpoint.storage.rename", exc=OSError,
                    checkpoint_id=checkpoint_id)
        if self.fs.exists(d):
            self.fs.delete(d, recursive=True)
        self.fs.rename(tmp, d)
        self.fs.fsync(self.job_dir)  # entry durability (see save())
        if not savepoint:
            self._retire_old()
        return CheckpointHandle(checkpoint_id, d, ts, savepoint,
                                epoch=self.epoch, size_bytes=_dir_size(d),
                                op_files=dict(op_files),
                                op_aux={n: dict(m)
                                        for n, m in aux_links.items()})

    def list_complete(self) -> List[CheckpointHandle]:
        out = []
        for name in self.fs.listdir(self.job_dir):
            if ".inprogress." in name:
                # an unrenamed writer dir is NOT complete even though
                # its manifest file exists inside (manifest-last only
                # holds for the FINAL name; a fenced/abandoned writer
                # leaves its tmp behind)
                continue
            d = os.path.join(self.job_dir, name)
            mf = os.path.join(d, "MANIFEST.json")
            if not self.fs.exists(mf):
                continue
            try:
                with self.fs.open_read(mf) as f:
                    m = json.loads(f.read().decode())
                out.append(CheckpointHandle(
                    m["checkpoint_id"], d, m["timestamp_ms"],
                    m.get("savepoint", False),
                    epoch=int(m.get("epoch", 0))))
            except (json.JSONDecodeError, KeyError):
                continue
        # (epoch, id) order — EPOCH FIRST: the epoch is the leadership
        # fencing token, so the newest timeline outranks any id from a
        # dead one. A deposed leader's late chk-9.e1 must not eclipse
        # the successor's chk-6..8.e2 (restoring the dead timeline
        # would rewind sources past output the live timeline's 2PC
        # sinks already committed); it also sorts FIRST here, so
        # retention retires it before anything live.
        return sorted(out, key=lambda h: (h.epoch, h.checkpoint_id))

    def latest(self) -> Optional[CheckpointHandle]:
        hs = [h for h in self.list_complete() if not h.is_savepoint]
        return hs[-1] if hs else None

    @staticmethod
    def load(handle_or_path) -> Dict[str, Any]:
        path = getattr(handle_or_path, "path", handle_or_path)
        fs = get_filesystem(path)
        mf_path = os.path.join(path, "MANIFEST.json")
        fmt = 1
        manifest: Dict[str, Any] = {}
        if fs.exists(mf_path):
            with fs.open_read(mf_path) as f:
                manifest = json.loads(f.read().decode())
            fmt = manifest.get("format_version", 1)
        comp = manifest.get("compression", "none")
        if fmt == 1 or manifest.get("layout") == "single":
            name = "state.blob" if fmt >= 3 else "state.pkl"
            with fs.open_read(os.path.join(path, name)) as f:
                return _decode_blob(_unpack(f.read(), comp))
        meta_name = "meta.blob" if fmt >= 3 else "meta.pkl"
        with fs.open_read(os.path.join(path, meta_name)) as f:
            payload = _decode_blob(_unpack(f.read(), comp))
        ops: Dict[Any, Any] = {}
        versions: Dict[Any, int] = {}
        for nid, entry in manifest.get("ops", {}).items():
            with fs.open_read(os.path.join(path, entry["file"])) as f:
                # node ids are ints in the live plan; the manifest's JSON
                # keys are strings — restore the original type. Blob
                # contents dispatch on magic bytes: a v3 checkpoint may
                # hardlink a v2 base's pickle blob and vice versa.
                ops[int(nid)] = _decode_blob(_unpack(f.read(), comp))
            versions[int(nid)] = entry["version"]
        payload["operators"] = ops
        payload["op_file_versions"] = versions
        payload["op_file_compression"] = comp
        payload["op_files"] = {
            int(nid): os.path.join(path, e["file"])
            for nid, e in manifest.get("ops", {}).items()}
        # changelog aux (lsm runs): resolve to absolute paths and
        # inject into each op snapshot so BOTH restore paths — the
        # driver's plain restore_state and repartition's merge — can
        # find the run files without re-reading the manifest
        aux_paths = {
            int(nid): {logical: os.path.join(path, fn)
                       for logical, fn in m.items()}
            for nid, m in manifest.get("aux", {}).items()}
        for nid, m in aux_paths.items():
            if isinstance(ops.get(nid), dict):
                ops[nid]["__aux_paths__"] = m
        payload["op_aux_paths"] = aux_paths
        return payload

    def _pack(self, raw: bytes) -> bytes:
        return zlib.compress(raw, 6) if self.compression == "zlib" else raw

    def _retire_old(self) -> None:
        """Best-effort retention: a retire/sweep failure must never fail
        the checkpoint that just committed (the old shutil path used
        ignore_errors=True; the seam re-establishes that contract for
        every backend, not just the local one)."""
        hs = [h for h in self.list_complete() if not h.is_savepoint]
        for h in hs[: -self.retained]:
            try:
                self.fs.delete(h.path, recursive=True)
            except OSError:
                pass
        # sweep orphaned in-progress dirs
        try:
            names = self.fs.listdir(self.job_dir)
        except OSError:
            names = []
        for name in names:
            if ".inprogress" in name:
                try:
                    self.fs.delete(os.path.join(self.job_dir, name),
                                   recursive=True)
                except OSError:
                    pass


def _dir_size(d: str) -> int:
    """Best-effort stats walk: a concurrently-retired directory (a
    restarted attempt's sweep) yields a partial size, never an error —
    size is telemetry, and the checkpoint already committed."""
    fs = get_filesystem(d)
    size = 0
    stack = [d]
    while stack:
        cur = stack.pop()
        try:
            names = fs.listdir(cur)
        except OSError:
            continue
        for name in names:
            p = os.path.join(cur, name)
            try:
                if fs.is_dir(p):
                    stack.append(p)
                else:
                    size += fs.size(p)
            except OSError:
                pass
    return size


def _unpack(raw: bytes, compression: str) -> bytes:
    return zlib.decompress(raw) if compression == "zlib" else raw


def _decode_blob(raw: bytes) -> Any:
    """Per-blob format dispatch on the magic bytes: v3 self-describing
    blobs decode via blobformat; anything else is a legacy v1/v2 pickle
    payload (still loadable — restore-across-upgrade)."""
    from flink_tpu.checkpoint import blobformat

    if blobformat.is_v3(raw):
        return blobformat.decode(raw)
    return pickle.loads(raw)
