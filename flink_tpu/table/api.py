"""Table API — relational view over DataStream pipelines.

ref role: flink-table-api-java (`TableEnvironment`, `Table` —
flink-table/flink-table-api-java/.../table/api/{TableEnvironment,
Table}.java) and the planner's lowering into DataStream-era ExecNodes
(flink-table-planner, SURVEY §3.8). Design difference, deliberately
TPU-first: there is no Calcite and no generated Java — a Table is a
thin logical wrapper over the SAME Transformation graph the DataStream
API builds, scalar expressions evaluate as vectorized numpy over the
columnar batches (expressions.py), and windowed grouped aggregation
lowers onto the device pane-state WindowOperator exactly like
``stream.key_by().window().aggregate()`` does. SQL (sql.py) parses into
these Table operations; both APIs meet the runtime at one seam.

Streaming semantics: a bare (non-windowed) GROUP BY over an unbounded
stream produces a CHANGELOG — continuous per-key updates. It lowers
onto the retract-mode running aggregation (ops/global_agg.py): each
emission retracts the key's previous row (-U) and asserts the new one
(+U), op-typed via records.OP_FIELD (ref: Flink's update/changelog
tables, table-runtime GroupAggFunction). Materialize the result
through a changelog-capable sink (RetractSink / UpsertSink) — the
analyzer's CHANGELOG_SINK_MISMATCH rule enforces this.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from flink_tpu.api.datastream import DataStream
from flink_tpu.api.windowing import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    WindowAssigner,
)
from flink_tpu.ops import aggregates
from flink_tpu.table.expressions import Aliased, Col, Expression, col

__all__ = [
    "TableEnvironment", "Table", "TableResult", "TableSchema",
    "Tumble", "Hop", "Session", "col",
]


# ---------------------------------------------------------------------------
# Window definitions (Table-API side; ref: table/api/{Tumble,Slide,
# Session}.java builders)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WindowDef:
    """A window spec over an event-time attribute."""
    assigner: WindowAssigner
    time_attr: Optional[str] = None  # None = the table's time attribute

    def on(self, time_attr: str) -> "WindowDef":
        return dataclasses.replace(self, time_attr=time_attr)


class Tumble:
    @staticmethod
    def over_ms(size_ms: int) -> WindowDef:
        return WindowDef(TumblingEventTimeWindows.of(size_ms))


class Hop:
    @staticmethod
    def of_ms(size_ms: int, slide_ms: int) -> WindowDef:
        return WindowDef(SlidingEventTimeWindows.of(size_ms, slide_ms))


class Session:
    @staticmethod
    def with_gap_ms(gap_ms: int) -> WindowDef:
        return WindowDef(EventTimeSessionWindows.with_gap(gap_ms))


# ---------------------------------------------------------------------------
# Aggregate call descriptors (SELECT list entries that are aggregates)
# ---------------------------------------------------------------------------

_AGG_FACTORIES = {
    "count": lambda f: aggregates.count(),
    "sum": lambda f: aggregates.sum_of(f),
    "max": lambda f: aggregates.max_of(f),
    "min": lambda f: aggregates.min_of(f),
    "avg": lambda f: aggregates.avg_of(f),
}

@dataclasses.dataclass(frozen=True)
class AggCall:
    fn: str                  # count/sum/max/min/avg
    field: Optional[str]     # None for count(*)
    out_name: str            # output column name

    @property
    def runtime_field(self) -> str:
        # the runtime's own default naming is the single source of
        # truth — ask the built lane rather than mirroring the
        # aggregates module's f"sum_{field}" conventions here
        return aggregates.result_fields(self.build())[0]

    def build(self) -> aggregates.LaneAggregate:
        if self.fn not in _AGG_FACTORIES:
            raise ValueError(f"unsupported aggregate {self.fn!r}")
        if self.fn != "count" and not self.field:
            raise ValueError(f"{self.fn}() needs a column argument")
        return _AGG_FACTORIES[self.fn](self.field)


# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TableSchema:
    columns: Tuple[str, ...]
    time_attr: Optional[str] = None  # event-time column (ms)

    def check(self, name: str) -> None:
        if name not in self.columns and name != self.time_attr:
            raise ValueError(
                f"column {name!r} not in schema {self.columns}")


class TableResult:
    """Materialized query result (ref: TableResult.collect)."""

    def __init__(self, rows: List[Dict[str, Any]], job_result=None) -> None:
        self.rows = rows
        self.job_result = job_result

    def collect(self) -> List[Dict[str, Any]]:
        return self.rows

    def to_pandas(self):  # optional convenience; pandas ships with the image
        import pandas as pd

        return pd.DataFrame(self.rows)


class TableEnvironment:
    """Catalog of named tables over one StreamExecutionEnvironment.
    ref: TableEnvironment.create / StreamTableEnvironment."""

    def __init__(self, env) -> None:
        self.env = env
        self._views: Dict[str, Table] = {}

    @classmethod
    def create(cls, env) -> "TableEnvironment":
        return cls(env)

    # -- catalog --------------------------------------------------------
    def create_temporary_view(self, name: str, table_or_stream,
                              schema: Optional[Sequence[str]] = None,
                              time_attr: Optional[str] = None) -> None:
        """Register a Table (or a DataStream + schema) under a name.
        ref: TableEnvironment.createTemporaryView."""
        if isinstance(table_or_stream, Table):
            self._views[name] = table_or_stream
        else:
            if schema is None:
                raise ValueError(
                    "registering a DataStream needs schema=[columns...]")
            self._views[name] = Table(
                self, table_or_stream,
                TableSchema(tuple(schema), time_attr))

    def from_data_stream(self, stream, schema: Sequence[str],
                         time_attr: Optional[str] = None) -> "Table":
        return Table(self, stream, TableSchema(tuple(schema), time_attr))

    def table(self, name: str) -> "Table":
        if name not in self._views:
            raise KeyError(
                f"no table {name!r}; registered: {sorted(self._views)}")
        return self._views[name]

    def sql_query(self, query: str) -> "Table":
        """Parse + plan a SQL query against the registered views.
        ref: TableEnvironment.sqlQuery (SURVEY §3.8 SQL parser/planner)."""
        from flink_tpu.table.sql import plan_sql

        return plan_sql(self, query)


class Table:
    """Logical relational view over a DataStream. Immutable — every
    operation returns a new Table wrapping a longer pipeline."""

    def __init__(self, t_env: TableEnvironment, stream: DataStream,
                 schema: TableSchema) -> None:
        self.t_env = t_env
        self.stream = stream
        self.schema = schema

    # -- row-level ------------------------------------------------------
    def filter(self, predicate: Expression) -> "Table":
        for f in predicate.fields():
            self.schema.check(f)

        def pred(data):
            return np.asarray(predicate.eval(data), bool)

        return Table(self.t_env, self.stream.filter(pred, name="sql_filter"),
                     self.schema)

    where = filter

    def select(self, *exprs: Union[str, Expression]) -> "Table":
        """Project/compute columns. Plain strings and Col pass through;
        computed expressions need .alias(name)."""
        parsed: List[Tuple[str, Expression]] = []
        for e in exprs:
            if isinstance(e, str):
                parsed.append((e, Col(e)))
            elif isinstance(e, Aliased):
                parsed.append((e.name, e.expr))
            elif isinstance(e, Col):
                parsed.append((e.name, e))
            else:
                raise ValueError(
                    f"computed select expression needs .alias(name): {e!r}")
        for _, e in parsed:
            for f in e.fields():
                self.schema.check(f)
        time_attr = self.schema.time_attr
        keep_time = time_attr in [n for n, _ in parsed]

        def project(data):
            n = len(next(iter(data.values()))) if data else 0
            out = {}
            for name, e in parsed:
                v = np.asarray(e.eval(data))
                if v.ndim == 0:  # literal column: broadcast to batch
                    v = np.full(n, v[()])
                out[name] = v
            return out

        out_cols = tuple(n for n, _ in parsed)
        return Table(
            self.t_env, self.stream.map(project, name="sql_project"),
            TableSchema(out_cols, time_attr if keep_time else None))

    # -- windowed grouped aggregation ----------------------------------
    def window(self, wdef: WindowDef) -> "WindowedTable":
        ta = wdef.time_attr or self.schema.time_attr
        if ta is None:
            raise ValueError(
                "window needs a time attribute: set time_attr on the "
                "table or use .on('ts_col')")
        return WindowedTable(self, dataclasses.replace(wdef, time_attr=ta))

    def group_by(self, *cols: Union[str, Col]) -> "GroupedTable":
        names = [c if isinstance(c, str) else c.name for c in cols]
        for n in names:
            self.schema.check(n)
        return GroupedTable(self, names, wdef=None)

    # -- execution ------------------------------------------------------
    def to_data_stream(self) -> DataStream:
        return self.stream

    def add_sink(self, sink) -> DataStream:
        return self.stream.add_sink(sink)

    def execute(self, job_name: str = "table-query") -> TableResult:
        """Run THIS query's lineage only — the environment may hold
        other queries' pipelines (each with sinks that must not re-fire;
        ref: TableEnvironment executes per-statement, not per-session)."""
        from flink_tpu.api.sinks import CollectSink

        sink = CollectSink()
        sink_stream = self.stream.add_sink(sink)
        keep = set()
        stack = [sink_stream.transform]
        while stack:
            t = stack.pop()
            if id(t) in keep:
                continue
            keep.add(id(t))
            stack.extend(t.inputs)
        lineage = [t for t in self.t_env.env._transforms if id(t) in keep]
        res = self.t_env.env.execute(job_name, transforms=lineage)
        return TableResult(sink.rows, res)


class WindowedTable:
    def __init__(self, table: Table, wdef: WindowDef) -> None:
        self.table = table
        self.wdef = wdef

    def group_by(self, *cols: Union[str, Col]) -> "GroupedTable":
        names = [c if isinstance(c, str) else c.name for c in cols]
        names = [n for n in names
                 if n not in ("window_start", "window_end")]
        for n in names:
            self.table.schema.check(n)
        return GroupedTable(self.table, names, self.wdef)

    def aggregate(self, *aggs: AggCall) -> Table:
        """Global (non-keyed) windowed aggregation → windowAll path."""
        return GroupedTable(self.table, [], self.wdef).aggregate(*aggs)


class GroupedTable:
    def __init__(self, table: Table, keys: List[str],
                 wdef: Optional[WindowDef]) -> None:
        if len(keys) > 1:
            raise ValueError(
                "v1 supports one grouping column (plus window_start/"
                f"window_end); got {keys}. Pre-combine keys with a "
                "select expression if needed.")
        self.table = table
        self.keys = keys
        self.wdef = wdef

    def window(self, wdef: WindowDef) -> "GroupedTable":
        ta = wdef.time_attr or self.table.schema.time_attr
        if ta is None:
            raise ValueError("window needs a time attribute")
        return GroupedTable(self.table, self.keys,
                            dataclasses.replace(wdef, time_attr=ta))

    def _aggregate_stream(self, *aggs: AggCall):
        """Build the windowed aggregation pipeline WITHOUT the output
        projection. Returns ``(agg_stream, pairs, key_out)`` where
        ``pairs`` maps each call's runtime result field to its SELECT
        alias (two aliases may share a runtime field: duplicate
        aggregates are computed once and fanned out at projection)."""
        if not aggs:
            raise ValueError("aggregate() needs at least one AggCall")
        uniq: Dict[Tuple[str, Optional[str]], AggCall] = {}
        for a in aggs:
            uniq.setdefault((a.fn, a.field), a)
        lanes = [a.build() for a in uniq.values()]
        lane = lanes[0] if len(lanes) == 1 else aggregates.multi(*lanes)
        stream = self.table.stream
        if self.wdef is None:
            # unwindowed GROUP BY → retract-mode running aggregation:
            # a changelog stream of op-typed rows, one -U/+U pair per
            # per-key update (the table-runtime GroupAggFunction shape)
            if not self.keys:
                raise ValueError(
                    "non-windowed aggregation without GROUP BY (a single "
                    "global running row) is not supported — group by a "
                    "key column, or add a window for append output")
            pairs = [(a.runtime_field, a.out_name) for a in aggs]
            agg_stream = (stream.key_by(self.keys[0])
                          .running_aggregate(lane, retract=True))
            return agg_stream, pairs, self.keys[0]
        ta = self.wdef.time_attr
        schema = self.table.schema
        if ta != schema.time_attr:
            raise ValueError(
                f"window is over {ta!r} but the table's event-time "
                f"attribute is {schema.time_attr!r}; timestamps/"
                "watermarks follow the source's declared attribute")

        if self.keys:
            key = self.keys[0]
            agg_stream = (stream.key_by(key)
                          .window(self.wdef.assigner)
                          .aggregate(lane))
            key_out: Optional[str] = key
        else:
            agg_stream = (stream.window_all(self.wdef.assigner)
                          .aggregate(lane))
            key_out = None
        pairs = [(a.runtime_field, a.out_name) for a in aggs]
        return agg_stream, pairs, key_out

    def aggregate(self, *aggs: AggCall) -> Table:
        agg_stream, pairs, key_out = self._aggregate_stream(*aggs)
        cols = [key_out] if key_out else []
        if self.wdef is not None:
            cols += ["window_start", "window_end"]
        return finish_projection(
            self.table.t_env, agg_stream, pairs, key_out,
            cols + [name for _, name in pairs])

def finish_projection(t_env: TableEnvironment, agg_stream, pairs,
                      key_out: Optional[str],
                      want: Sequence[str]) -> Table:
    """Shared output projection for aggregations: rename the runtime
    result fields (key/window_start/window_end/<agg lanes>) to the
    SELECT aliases, emitting exactly ``want`` columns in order — plus
    the changelog op column when the input carries one (the op lane is
    runtime metadata riding OUTSIDE the SELECT list; a projection that
    dropped it would turn retractions back into inserts)."""
    from flink_tpu.records import OP_FIELD

    def finish(data):
        out: Dict[str, np.ndarray] = {}
        for name in want:
            if name == key_out:
                out[name] = data["key"]
            elif name in ("window_start", "window_end"):
                out[name] = data[name]
        for rt, name in pairs:
            if name in want:
                out[name] = data[rt]
        if OP_FIELD in data:
            out[OP_FIELD] = data[OP_FIELD]
        return out

    return Table(t_env, agg_stream.map(finish, name="sql_agg_project"),
                 TableSchema(tuple(want)))
