"""Table & SQL API over the DataStream runtime (SURVEY §3.8).

Two equivalent frontends, one lowering:

    t_env = TableEnvironment.create(env)
    t_env.create_temporary_view("bids", stream, schema=[...], time_attr="ts")
    t_env.sql_query('''
        SELECT auction, window_end, COUNT(*) AS bid_count
        FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts),
                       INTERVAL '1' SECOND, INTERVAL '10' SECOND))
        GROUP BY auction, window_start, window_end
        ORDER BY bid_count DESC LIMIT 1
    ''').execute()

or the fluent Table API: ``table.window(Hop.of_ms(10_000, 1_000))
.group_by("auction").aggregate(AggCall("count", None, "bid_count"))``.
"""
from flink_tpu.table.api import (
    AggCall,
    Hop,
    Session,
    Table,
    TableEnvironment,
    TableResult,
    TableSchema,
    Tumble,
)
from flink_tpu.table.expressions import col, lit
from flink_tpu.table.sql import SqlError

__all__ = [
    "AggCall", "Hop", "Session", "Table", "TableEnvironment",
    "TableResult", "TableSchema", "Tumble", "col", "lit", "SqlError",
]
