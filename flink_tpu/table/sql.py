"""SQL frontend — tokenizer + recursive-descent parser + planner for a
streaming SQL subset, lowering onto the Table API (and through it onto
the device pane-state runtime).

ref role: flink-sql-parser (Calcite dialect) + flink-table-planner
(SURVEY §3.8). Deliberately NOT a Calcite port: the supported subset is
chosen to cover the windowed streaming queries the runtime executes
natively, and each query plans in one pass with no optimizer — the
heavy lifting (window slicing, pane state, top-n) already lives in the
compiled device kernels, so the planner's only job is a faithful
lowering. Unsupported constructs raise ``SqlError`` with the offending
token position rather than silently degrading.

Supported grammar (case-insensitive keywords):

    SELECT sel [, sel ...]
    FROM source [[AS] ident]
         [JOIN source [[AS] ident] ON eq [AND eq ...]]
    [WHERE expr]
    [GROUP BY ident [, ident ...]]
    [HAVING expr]
    [ORDER BY ident [DESC] LIMIT n | LIMIT n]

    eq     := [ident.]ident = [ident.]ident     (JOIN: one cross-side
              key equality; window_start/window_end equalities allowed
              and tautological under the shared window spec)

    sel    := expr [AS ident] | agg(arg) [AS ident] | *
    agg    := COUNT(*|col) | {SUM|MAX|MIN|AVG}(col-or-expression)
    source := ident
            | TABLE(TUMBLE(TABLE ident, DESCRIPTOR(col), interval))
            | TABLE(HOP(TABLE ident, DESCRIPTOR(col), interval, interval))
            | TABLE(SESSION(TABLE ident, DESCRIPTOR(col), interval))
    interval := INTERVAL 'n' {MILLISECOND|SECOND|MINUTE|HOUR|DAY}[S]
    expr   := OR-expr over AND / NOT / comparisons / + - * / % / ( )
              with idents, numbers, 'strings'

Window TVFs follow FLIP-145 (the windowing table-valued functions of
Flink SQL): HOP's interval order is (slide, size), and the TVF adds
``window_start``/``window_end`` columns which GROUP BY then uses.
ORDER BY <agg-alias> DESC LIMIT n on a windowed aggregation lowers to
the fused device top-n (per-window RANK() <= n, Q5's hot-items shape).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Tuple

from flink_tpu.table.api import (
    _AGG_FACTORIES,
    AggCall,
    Hop,
    Session,
    Table,
    TableEnvironment,
    Tumble,
    finish_projection,
)
from flink_tpu.table.expressions import BinOp, Col, Expression, Lit, UnaryOp

__all__ = ["SqlError", "plan_sql", "parse"]


class SqlError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<num>\d+(?:\.\d+)?)"
    r"|(?P<str>'(?:[^']|'')*')"
    r"|(?P<ident>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op><>|<=|>=|!=|=|<|>|\(|\)|,|\*|\+|-|/|%|\.)"
    r")")


@dataclasses.dataclass
class Tok:
    kind: str  # num/str/ident/op/kw
    text: str
    pos: int


_KEYWORDS = {
    "select", "from", "where", "group", "by", "order", "limit", "desc",
    "asc", "as", "and", "or", "not", "table", "tumble", "hop", "session",
    "descriptor", "interval", "having", "join", "on",
}


def _tokenize(sql: str) -> List[Tok]:
    out: List[Tok] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m or m.end() == i:
            if sql[i:].strip():
                raise SqlError(f"cannot tokenize at position {i}: "
                               f"{sql[i:i+20]!r}")
            break
        i = m.end()
        if m.lastgroup == "ident":
            text = m.group("ident")
            kind = "kw" if text.lower() in _KEYWORDS else "ident"
            out.append(Tok(kind, text.lower() if kind == "kw" else text,
                           m.start()))
        elif m.lastgroup == "num":
            out.append(Tok("num", m.group("num"), m.start()))
        elif m.lastgroup == "str":
            out.append(Tok("str", m.group("str")[1:-1].replace("''", "'"),
                           m.start()))
        else:
            out.append(Tok("op", m.group("op"), m.start()))
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SelectItem:
    expr: Optional[Expression]        # scalar expression, or None if agg
    agg: Optional[Tuple[str, Optional[str]]]  # (fn, col) for aggregates
    alias: Optional[str]
    star: bool = False


@dataclasses.dataclass
class WindowTvf:
    kind: str          # tumble/hop/session
    table: str
    time_col: str
    intervals: List[int]  # ms


@dataclasses.dataclass
class JoinSource:
    """FROM <tvf> [AS a] JOIN <tvf> [AS b] ON conjunction-of-equalities
    (FLIP-145 window join shape). Each condition is a pair of
    (qualifier-or-None, column) references."""

    left: Any
    left_alias: Optional[str]
    right: Any
    right_alias: Optional[str]
    conds: List[Tuple[Tuple[Optional[str], str], Tuple[Optional[str], str]]]


@dataclasses.dataclass
class Query:
    items: List[SelectItem]
    source: Any                 # str table name | WindowTvf | JoinSource
    where: Optional[Expression]
    group_by: List[str]
    having: Optional[Expression]
    order_by: Optional[Tuple[str, bool]]  # (col, desc)
    limit: Optional[int]


class _Parser:
    def __init__(self, toks: List[Tok]) -> None:
        self.toks = toks
        self.i = 0

    # -- plumbing -------------------------------------------------------
    def peek(self) -> Optional[Tok]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tok:
        t = self.peek()
        if t is None:
            raise SqlError("unexpected end of query")
        self.i += 1
        return t

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Tok]:
        t = self.peek()
        if t and t.kind == kind and (text is None or t.text == text):
            self.i += 1
            return t
        return None

    def expect(self, kind: str, text: Optional[str] = None) -> Tok:
        t = self.accept(kind, text)
        if t is None:
            got = self.peek()
            raise SqlError(
                f"expected {text or kind}, got "
                f"{(got.text if got else 'end of query')!r}"
                + (f" at position {got.pos}" if got else ""))
        return t

    # -- grammar --------------------------------------------------------
    def query(self) -> Query:
        self.expect("kw", "select")
        items = [self.select_item()]
        while self.accept("op", ","):
            items.append(self.select_item())
        self.expect("kw", "from")
        source = self.source()
        left_alias = self.alias()
        if self.accept("kw", "join"):
            right = self.source()
            right_alias = self.alias()
            self.expect("kw", "on")
            conds = [self.join_eq()]
            while self.accept("kw", "and"):
                conds.append(self.join_eq())
            source = JoinSource(source, left_alias, right, right_alias,
                                conds)
        where = None
        if self.accept("kw", "where"):
            where = self.expr()
        group_by: List[str] = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.expect("ident").text)
            while self.accept("op", ","):
                group_by.append(self.expect("ident").text)
        having = None
        if self.accept("kw", "having"):
            having = self.expr()
        order_by = None
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            c = self.expect("ident").text
            desc = bool(self.accept("kw", "desc"))
            if not desc:
                self.accept("kw", "asc")
            order_by = (c, desc)
        limit = None
        if self.accept("kw", "limit"):
            ltok = self.expect("num")
            if "." in ltok.text:
                raise SqlError(f"LIMIT must be an integer, got {ltok.text}")
            limit = int(ltok.text)
        t = self.peek()
        if t is not None:
            raise SqlError(f"unexpected trailing input at position "
                           f"{t.pos}: {t.text!r}")
        return Query(items, source, where, group_by, having, order_by,
                     limit)

    def select_item(self) -> SelectItem:
        if self.accept("op", "*"):
            return SelectItem(None, None, None, star=True)
        t = self.peek()
        if (t and t.kind == "ident"
                and t.text.lower() in _AGG_FACTORIES
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1].text == "("):
            fn = self.next().text.lower()
            self.expect("op", "(")
            if self.accept("op", "*"):
                arg = None
                if fn != "count":
                    raise SqlError(f"{fn}(*) is not valid; only COUNT(*)")
            else:
                # full expression argument: SUM(a * b), AVG(p + t), ...
                # plain columns stay strings; expressions lower through
                # a derived pre-projection in the planner
                e = self.expr()
                arg = e.name if isinstance(e, Col) else e
            self.expect("op", ")")
            alias = self.alias()
            return SelectItem(None, (fn, arg), alias)
        e = self.expr()
        return SelectItem(e, None, self.alias())

    def join_eq(self) -> Tuple[Tuple[Optional[str], str],
                               Tuple[Optional[str], str]]:
        a = self.qualified_ref()
        self.expect("op", "=")
        return (a, self.qualified_ref())

    def qualified_ref(self) -> Tuple[Optional[str], str]:
        n1 = self.expect("ident").text
        if self.accept("op", "."):
            return (n1, self.expect("ident").text)
        return (None, n1)

    def alias(self) -> Optional[str]:
        if self.accept("kw", "as"):
            return self.expect("ident").text
        t = self.peek()
        if t and t.kind == "ident":
            return self.next().text
        return None

    def source(self):
        if self.accept("kw", "table"):
            self.expect("op", "(")
            kind_tok = self.next()
            kind = kind_tok.text
            if kind not in ("tumble", "hop", "session"):
                raise SqlError(
                    f"unsupported table function {kind!r} (TUMBLE/HOP/"
                    "SESSION)")
            self.expect("op", "(")
            self.expect("kw", "table")
            name = self.expect("ident").text
            self.expect("op", ",")
            self.expect("kw", "descriptor")
            self.expect("op", "(")
            time_col = self.expect("ident").text
            self.expect("op", ")")
            intervals = []
            while self.accept("op", ","):
                intervals.append(self.interval_ms())
            self.expect("op", ")")
            self.expect("op", ")")
            need = {"tumble": 1, "hop": 2, "session": 1}[kind]
            if len(intervals) != need:
                raise SqlError(
                    f"{kind.upper()} takes {need} interval(s), got "
                    f"{len(intervals)}")
            return WindowTvf(kind, name, time_col, intervals)
        return self.expect("ident").text

    _UNIT_MS = {
        "millisecond": 1, "second": 1000, "minute": 60_000,
        "hour": 3_600_000, "day": 86_400_000,
    }

    def interval_ms(self) -> int:
        self.expect("kw", "interval")
        val = self.expect("str").text
        unit_tok = self.expect("ident")
        unit = unit_tok.text.lower().rstrip("s")
        if unit not in self._UNIT_MS:
            raise SqlError(f"unknown interval unit {unit_tok.text!r}")
        try:
            n = float(val)
        except ValueError:
            raise SqlError(f"bad interval value {val!r}") from None
        return int(n * self._UNIT_MS[unit])

    # -- expressions (precedence climbing) ------------------------------
    def expr(self) -> Expression:
        return self.or_expr()

    def or_expr(self) -> Expression:
        e = self.and_expr()
        while self.accept("kw", "or"):
            e = BinOp("or", e, self.and_expr())
        return e

    def and_expr(self) -> Expression:
        e = self.not_expr()
        while self.accept("kw", "and"):
            e = BinOp("and", e, self.not_expr())
        return e

    def not_expr(self) -> Expression:
        if self.accept("kw", "not"):
            return UnaryOp("not", self.not_expr())
        return self.comparison()

    _CMP = {"=": "==", "<>": "!=", "!=": "!=", "<": "<", "<=": "<=",
            ">": ">", ">=": ">="}

    def comparison(self) -> Expression:
        e = self.additive()
        t = self.peek()
        if t and t.kind == "op" and t.text in self._CMP:
            op = self._CMP[self.next().text]
            return BinOp(op, e, self.additive())
        return e

    def additive(self) -> Expression:
        e = self.multiplicative()
        while True:
            t = self.peek()
            if t and t.kind == "op" and t.text in ("+", "-"):
                e = BinOp(self.next().text, e, self.multiplicative())
            else:
                return e

    def multiplicative(self) -> Expression:
        e = self.unary()
        while True:
            t = self.peek()
            if t and t.kind == "op" and t.text in ("*", "/", "%"):
                e = BinOp(self.next().text, e, self.unary())
            else:
                return e

    def unary(self) -> Expression:
        if self.accept("op", "-"):
            return UnaryOp("neg", self.unary())
        return self.primary()

    def primary(self) -> Expression:
        t = self.next()
        if t.kind == "num":
            return Lit(float(t.text) if "." in t.text else int(t.text))
        if t.kind == "str":
            return Lit(t.text)
        if t.kind == "ident":
            if self.accept("op", "."):
                # qualified reference (join queries): kept as a dotted
                # Col name; the join planner resolves the qualifier
                return Col(f"{t.text}.{self.expect('ident').text}")
            return Col(t.text)
        if t.kind == "op" and t.text == "(":
            e = self.expr()
            self.expect("op", ")")
            return e
        raise SqlError(f"unexpected token {t.text!r} at position {t.pos}")


def parse(sql: str) -> Query:
    return _Parser(_tokenize(sql)).query()


# ---------------------------------------------------------------------------
# Planner: Query AST -> Table pipeline
# ---------------------------------------------------------------------------

def plan_sql(t_env: "TableEnvironment", sql: str) -> "Table":
    q = parse(sql)

    if isinstance(q.source, JoinSource):
        return _plan_join(t_env, q)

    # resolve source
    if isinstance(q.source, WindowTvf):
        base = t_env.table(q.source.table)
        iv = q.source.intervals
        if q.source.kind == "tumble":
            wdef = Tumble.over_ms(iv[0])
        elif q.source.kind == "hop":
            # FLIP-145 HOP argument order: (slide, size)
            wdef = Hop.of_ms(size_ms=iv[1], slide_ms=iv[0])
        else:
            wdef = Session.with_gap_ms(iv[0])
        wdef = wdef.on(q.source.time_col)
    else:
        base = t_env.table(q.source)
        wdef = None

    table = base
    if q.where is not None:
        table = table.filter(q.where)

    aggs = [it for it in q.items if it.agg is not None]
    if aggs:
        return _plan_aggregate(q, table, wdef)

    # pure projection query
    if q.having is not None:
        raise SqlError("HAVING without aggregate functions in SELECT")
    if wdef is not None:
        raise SqlError(
            "a window TVF source needs aggregate functions in SELECT "
            "(per-row window column attachment is not in v1)")
    if q.group_by:
        raise SqlError(
            "GROUP BY without aggregate functions in SELECT")
    if q.order_by is not None or q.limit is not None:
        raise SqlError(
            "ORDER BY/LIMIT is only supported over a windowed "
            "aggregation (per-window top-n)")
    if any(it.star for it in q.items):
        if len(q.items) != 1:
            raise SqlError("SELECT * cannot mix with other columns")
        return table
    sels = []
    for it in q.items:
        e = it.expr
        name = it.alias or (e.name if isinstance(e, Col) else None)
        if name is None:
            raise SqlError(f"computed column needs AS alias: {e!r}")
        sels.append(e.alias(name))
    return table.select(*sels)


def _plan_join(t_env: "TableEnvironment", q: Query) -> "Table":
    """Windowed equi-join (FLIP-145 window join): both sides are the
    SAME window TVF, ON carries exactly one cross-side key equality
    (plus optional window_start/window_end equalities, which the shared
    window spec makes tautological). Lowers onto the DataStream windowed
    join (ops/join.py, Q8's exact-pairs operator). Everything outside
    this shape raises SqlError naming what is unsupported."""
    from flink_tpu.api.windowing import (
        SlidingEventTimeWindows, TumblingEventTimeWindows)
    from flink_tpu.table.api import Table, TableSchema

    src: JoinSource = q.source
    if q.group_by or any(it.agg for it in q.items):
        return _plan_join_aggregate(t_env, q)
    if q.order_by is not None or q.limit is not None:
        raise SqlError("ORDER BY/LIMIT over a JOIN is not supported")
    if not isinstance(src.left, WindowTvf) or not isinstance(
            src.right, WindowTvf):
        raise SqlError(
            "streaming JOIN requires a window TVF on BOTH sides "
            "(an unbounded join has unbounded state); wrap each input "
            "in TABLE(TUMBLE(...)/HOP(...))")
    l, r = src.left, src.right
    if l.kind == "session" or r.kind == "session":
        raise SqlError("SESSION window JOIN is not supported")
    if (l.kind, l.intervals) != (r.kind, r.intervals):
        raise SqlError(
            f"JOIN sides must share one window spec, got "
            f"{l.kind.upper()}{l.intervals} vs {r.kind.upper()}"
            f"{r.intervals}")
    lt = t_env.table(l.table)
    rt = t_env.table(r.table)
    lname = src.left_alias or l.table
    rname = src.right_alias or r.table
    if lname == rname:
        raise SqlError(f"ambiguous join side name {lname!r} — alias one")

    def side_of(ref: Tuple[Optional[str], str], ctx: str) -> str:
        qual, col = ref
        if qual == lname:
            return "L"
        if qual == rname:
            return "R"
        if qual is not None:
            raise SqlError(f"unknown qualifier {qual!r} in {ctx}")
        in_l = col in lt.schema.columns
        in_r = col in rt.schema.columns
        if in_l and in_r:
            raise SqlError(
                f"column {col!r} in {ctx} is ambiguous — qualify it "
                f"with {lname!r} or {rname!r}")
        if in_l:
            return "L"
        if in_r:
            return "R"
        raise SqlError(f"unknown column {col!r} in {ctx}")

    key_pairs = []
    for a, b in src.conds:
        if a[1] in ("window_start", "window_end") and a[1] == b[1]:
            continue  # tautological under the shared window spec
        sa, sb = side_of(a, "ON"), side_of(b, "ON")
        if sa == sb:
            raise SqlError(
                "ON condition must compare columns across the two "
                f"sides, got both from one side: {a[1]} = {b[1]}")
        key_pairs.append((a[1], b[1]) if sa == "L" else (b[1], a[1]))
    if len(key_pairs) != 1:
        raise SqlError(
            f"exactly one cross-side key equality is supported, got "
            f"{len(key_pairs)}")
    lk, rk = key_pairs[0]
    lt.schema.check(lk)
    rt.schema.check(rk)

    # selected fields decide what each side carries through the join
    out_names: List[str] = []
    l_fields: List[str] = []
    r_fields: List[str] = []
    plan: List[Tuple[str, str]] = []  # (runtime field, output name)
    for it in q.items:
        if it.star:
            raise SqlError(
                "SELECT * over a JOIN is not supported — name the "
                "columns (output schema would be ambiguous)")
        if not isinstance(it.expr, Col):
            raise SqlError(
                "JOIN SELECT items must be plain columns in v1")
        name = it.expr.name
        qual, col = (name.split(".", 1) if "." in name else (None, name))
        out = it.alias or col
        if col in ("window_start", "window_end") or (
                qual is None and col in (lk, rk) and lk == rk):
            plan.append((col if col.startswith("window_") else "key", out))
            out_names.append(out)
            continue
        side = side_of((qual, col), "SELECT")
        if side == "L":
            if col == lk:
                plan.append(("key", out))
            else:
                l_fields.append(col)
                plan.append((f"left_{col}", out))
        else:
            if col == rk:
                plan.append(("key", out))
            else:
                r_fields.append(col)
                plan.append((f"right_{col}", out))
        out_names.append(out)

    joined = (lt.stream.join(rt.stream)
              .where(lk).equal_to(rk)
              .window(TumblingEventTimeWindows.of(l.intervals[0])
                      if l.kind == "tumble"
                      else SlidingEventTimeWindows.of(
                          l.intervals[1], l.intervals[0]))
              .apply(left_fields=tuple(dict.fromkeys(l_fields)),
                     right_fields=tuple(dict.fromkeys(r_fields)),
                     name="sql_window_join"))

    def project(data):
        return {out: data[fieldname] for fieldname, out in plan}

    out_stream = joined.map(project, name="sql_join_project")
    table = Table(t_env, out_stream, TableSchema(tuple(out_names)))
    if q.where is not None:
        table = table.filter(q.where)
    return table


def _plan_join_aggregate(t_env: "TableEnvironment", q: Query) -> "Table":
    """Aggregation over a window JOIN — ``SELECT k, AGG(x) FROM
    TABLE(TUMBLE(a)) JOIN TABLE(TUMBLE(b)) ON ... GROUP BY k`` (the
    Nexmark Q8-then-count shape). Plans as join → derived stream →
    re-window → aggregate: the joined rows carry the pane they were
    produced in as their stream timestamp (window_end - 1, the driver's
    fired-row stamping), so re-assigning them with the SAME tumbling
    spec lands every row back in its own pane — which is exactly why
    only TUMBLE qualifies (a sliding assigner would fan each joined row
    into ``size/slide`` windows, multi-counting it)."""
    src: JoinSource = q.source
    l, r = src.left, src.right
    if not isinstance(l, WindowTvf) or not isinstance(r, WindowTvf):
        raise SqlError(
            "streaming JOIN requires a window TVF on BOTH sides "
            "(an unbounded join has unbounded state); wrap each input "
            "in TABLE(TUMBLE(...))")
    if l.kind != "tumble" or r.kind != "tumble":
        raise SqlError(
            "aggregation over a JOIN supports TUMBLE windows only: "
            "joined rows re-window by their pane timestamp, which only "
            "tumbling panes make unambiguous (a HOP row belongs to "
            "several windows)")
    if q.order_by is not None or q.limit is not None:
        raise SqlError(
            "ORDER BY/LIMIT over a JOIN aggregation is not supported — "
            "aggregate into a view first")
    group_cols = [g for g in q.group_by
                  if g not in ("window_start", "window_end")]
    if len(group_cols) != 1:
        raise SqlError(
            "aggregation over a JOIN needs exactly one non-window "
            f"grouping column; got {group_cols}")

    # columns the derived (joined) stream must carry: the grouping
    # column, every aggregate argument, and WHERE references — each
    # projected to its UNQUALIFIED name
    needed: dict = {}  # out name -> (possibly qualified) source ref

    def need(ref: str, ctx: str) -> str:
        base = ref.split(".", 1)[1] if "." in ref else ref
        if base in ("window_start", "window_end"):
            return base  # re-derived by the downstream window
        prev = needed.get(base)
        if prev is not None and prev != ref:
            # an unqualified ref names the same column as its qualified
            # twin (GROUP BY columns parse unqualified); only two
            # DIFFERENT qualified refs are a genuine cross-side clash
            if ref == base:
                return base
            if prev != base:
                raise SqlError(
                    f"column name {base!r} is needed from both join "
                    f"sides ({prev} and {ref} in {ctx}) — alias one "
                    "side's column")
        needed[base] = ref
        return base

    items3: List[SelectItem] = []
    for it in q.items:
        if it.star:
            raise SqlError("SELECT * cannot mix with aggregates")
        if it.agg is not None:
            fn, arg = it.agg
            if arg is not None and not isinstance(arg, str):
                raise SqlError(
                    f"{fn.upper()}(<expression>) over a JOIN is not "
                    "supported — aggregate arguments must be plain "
                    "columns")
            arg3 = need(arg, "SELECT") if arg is not None else None
            items3.append(SelectItem(None, (fn, arg3), it.alias))
        else:
            if not isinstance(it.expr, Col):
                raise SqlError(
                    "non-aggregate SELECT items over a JOIN aggregation "
                    f"must be plain columns, got {it.expr!r}")
            items3.append(SelectItem(
                Col(need(it.expr.name, "SELECT")), None, it.alias))
    for g in group_cols:
        need(g, "GROUP BY")
    if q.where is not None:
        for f in q.where.fields():
            need(f, "WHERE")

    # phase 1: the plain window join, projecting exactly the needed
    # columns under their unqualified names (reuses the whole join
    # validation/lowering path)
    q2 = Query(
        items=[SelectItem(Col(ref), None, out)
               for out, ref in needed.items()],
        source=src, where=q.where, group_by=[], having=None,
        order_by=None, limit=None)
    joined = _plan_join(t_env, q2)

    # phase 2: re-key and re-window the derived stream with the same
    # tumbling spec; the synthetic time attribute names the stream
    # timestamp (joined rows are stamped window_end - 1 by the driver —
    # no column carries it)
    from flink_tpu.table.api import Table, TableSchema
    tbl = Table(t_env, joined.stream,
                TableSchema(joined.schema.columns, time_attr="__rowtime__"))
    wdef = Tumble.over_ms(l.intervals[0]).on("__rowtime__")
    q3 = Query(items=items3, source=l.table, where=None,
               group_by=q.group_by, having=q.having, order_by=None,
               limit=None)
    return _plan_aggregate(q3, tbl, wdef)


def _plan_running_aggregate(q: Query, table: "Table", group_cols,
                            calls, plain) -> "Table":
    """`SELECT k, agg FROM t GROUP BY k` with NO window TVF: the
    canonical streaming-SQL shape emitting a CHANGELOG. Lowers onto the
    retract-mode KeyedStream.running_aggregate (ops/global_agg.py): each
    per-key update emits a -U retraction of the previous row and a +U
    assertion of the new one, op-typed via records.OP_FIELD (ref:
    table-runtime GroupAggFunction). Materialize with a
    changelog-capable sink — ``RetractSink``/``UpsertSink`` — or window
    the changelog downstream (the changelog_* lanes in ops/aggregates
    fold retractions).

    HAVING is a per-row filter over the op-typed rows, and the case
    analysis is exactly why that is correct: a key UPDATING INTO the
    predicate passes only its +U (an insert to the view); a key
    updating OUT of it passes only its -U, which changelog-capable
    sinks treat as the key's deletion."""
    from flink_tpu.ops import aggregates
    from flink_tpu.table.api import finish_projection

    if q.order_by is not None or q.limit is not None:
        raise SqlError(
            "ORDER BY/LIMIT over an unwindowed aggregation would need "
            "a continuously re-ranked changelog; use a window TVF")
    if len(group_cols) != 1:
        raise SqlError(
            "an unwindowed aggregate needs exactly one grouping "
            f"column in v1; got {group_cols}")
    uniq = {}
    for c in calls:
        uniq.setdefault((c.fn, c.field), c)
    lanes = [c.build() for c in uniq.values()]
    lane = lanes[0] if len(lanes) == 1 else aggregates.multi(*lanes)
    key = group_cols[0]
    agg_stream = table.stream.key_by(key).running_aggregate(
        lane, retract=True)
    pairs = [(c.runtime_field, c.out_name) for c in calls]
    want = plain + [c.out_name for c in calls]
    result = finish_projection(table.t_env, agg_stream, pairs,
                               key if key in plain else None, want)
    if q.having is not None:
        # row-level filter over the changelog (op column rides through
        # the filter untouched): -U rows that leave the predicate while
        # their +U partner stays inside become genuine deletions
        result = result.filter(q.having)
    return result


def _plan_aggregate(q: Query, table: "Table",
                    wdef) -> "Table":
    group_cols = [g for g in q.group_by
                  if g not in ("window_start", "window_end")]
    if wdef is None and any(
            g in ("window_start", "window_end") for g in q.group_by):
        raise SqlError(
            "window_start/window_end grouping needs a window TVF source")
    if len(group_cols) > 1:
        raise SqlError(
            f"v1 supports one non-window grouping column; got "
            f"{group_cols}")

    # build agg calls with output names; EXPRESSION arguments
    # (SUM(a*b), AVG(p+t), ...) lower through a derived pre-projection
    # computed before the window aggregation — the streaming equivalent
    # of the planner's calc-before-agg rewrite
    calls: List[AggCall] = []
    plain: List[str] = []
    derived: List[Tuple[str, Expression]] = []
    for it in q.items:
        if it.star:
            raise SqlError("SELECT * cannot mix with aggregates")
        if it.agg is not None:
            fn, arg = it.agg
            if arg is not None and not isinstance(arg, str):
                if it.alias is None:
                    raise SqlError(
                        f"{fn.upper()}(<expression>) needs an AS alias")
                name = f"__agg_expr_{len(derived)}"
                derived.append((name, arg))
                arg = name
            default = fn if fn == "count" else f"{fn}_{arg}"
            calls.append(AggCall(fn, arg, it.alias or default))
        else:
            e = it.expr
            if not isinstance(e, Col):
                raise SqlError(
                    "non-aggregate SELECT items in a grouped query must "
                    f"be plain grouping columns, got {e!r}")
            plain.append(it.alias or e.name)
            if it.alias and it.alias != e.name:
                raise SqlError(
                    "aliasing grouping columns is not supported in v1")
    allowed = set(group_cols) | {"window_start", "window_end"}
    for p in plain:
        if p not in allowed:
            raise SqlError(
                f"column {p!r} in SELECT is neither grouped nor "
                "aggregated")

    if derived:
        # keep the grouping columns, the time attribute, and every
        # plain aggregate argument alongside the derived columns
        keep = list(dict.fromkeys(
            group_cols
            + ([q.source.time_col] if wdef is not None else [])
            + [c.field for c in calls
               if isinstance(c.field, str)
               and not c.field.startswith("__agg_expr_")]))
        sels = [Col(k).alias(k) for k in keep]
        sels += [e.alias(name) for name, e in derived]
        table = table.select(*sels)
    if wdef is None:
        return _plan_running_aggregate(q, table, group_cols, calls,
                                       plain)
    gt = (table.window(wdef).group_by(*q.group_by)
          if q.group_by else table.window(wdef).group_by())
    want = plain + [c.out_name for c in calls]

    # ORDER BY <agg output> DESC LIMIT n -> fused device per-window top-n
    if q.order_by is not None or q.limit is not None:
        if q.order_by is None or q.limit is None:
            raise SqlError("ORDER BY and LIMIT must appear together")
        by_col, desc = q.order_by
        if not desc:
            raise SqlError(
                "only ORDER BY <agg> DESC LIMIT n (per-window top-n) "
                "is supported")
        by_call = next((c for c in calls if c.out_name == by_col), None)
        if by_call is None:
            raise SqlError(
                f"ORDER BY column {by_col!r} must be one of the "
                f"aggregates {[c.out_name for c in calls]}")
        if not group_cols:
            raise SqlError(
                "ORDER BY ... DESC LIMIT n ranks keys within each "
                "window and needs a grouping column; a global windowed "
                "aggregate has one row per window already")
        agg_stream, pairs, key_out = gt._aggregate_stream(*calls)
        if not hasattr(agg_stream, "top"):
            # session windows aggregate through the merge registry, not
            # the pane fire path that hosts the fused top-n
            raise SqlError(
                "ORDER BY ... DESC LIMIT n is not supported over "
                "SESSION windows in v1 (TUMBLE/HOP only)")
        topped = agg_stream.top(q.limit, by=by_call.runtime_field)
        out = finish_projection(
            table.t_env, topped, pairs, key_out, want)
        if q.having is not None:
            for f in q.having.fields():
                if f not in out.schema.columns:
                    raise SqlError(
                        f"HAVING references {f!r}, which the top-n "
                        "output does not carry — select it")
            out = out.filter(q.having)
        return out

    result = gt.aggregate(*calls)
    # HAVING filters the AGGREGATED rows (it may reference aggregate
    # aliases and grouping columns — the full pre-projection schema)
    if q.having is not None:
        result = result.filter(q.having)
    # drop columns not selected (grouping col might be omitted)
    if set(want) != set(result.schema.columns):
        result = result.select(*want)
    return result
