"""Expression DSL for the Table API — scalar expressions over record
batches, evaluated as vectorized numpy over column dicts.

ref role: flink-table-api-java's ``Expressions`` /
``ApiExpressionUtils`` trees (flink-table/flink-table-api-java/.../
table/api/Expressions.java) and the planner's code generation
(flink-table-planner codegen, SURVEY §3.8) — except here "codegen" is
just numpy broadcasting over the already-columnar batch, so a compiled
expression is a plain Python closure ``dict[str, ndarray] -> ndarray``.
No Janino, no Calcite: the batch layout IS the binary row format.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict

import numpy as np

Batch = Dict[str, np.ndarray]


class Expression:
    """Node in a scalar expression tree. Subclasses implement
    ``eval(batch) -> ndarray`` (vectorized, one value per record)."""

    def eval(self, batch: Batch) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def fields(self) -> set:
        """Column names this expression reads."""
        return set()

    # -- operator sugar (both Table API and the SQL planner build these)
    def _bin(self, op: str, other: Any, flip: bool = False) -> "Expression":
        o = other if isinstance(other, Expression) else Lit(other)
        return BinOp(op, o, self) if flip else BinOp(op, self, o)

    def __eq__(self, other):  # type: ignore[override]
        return self._bin("==", other)

    def __ne__(self, other):  # type: ignore[override]
        return self._bin("!=", other)

    def __lt__(self, other):
        return self._bin("<", other)

    def __le__(self, other):
        return self._bin("<=", other)

    def __gt__(self, other):
        return self._bin(">", other)

    def __ge__(self, other):
        return self._bin(">=", other)

    def __add__(self, other):
        return self._bin("+", other)

    def __radd__(self, other):
        return self._bin("+", other, flip=True)

    def __sub__(self, other):
        return self._bin("-", other)

    def __rsub__(self, other):
        return self._bin("-", other, flip=True)

    def __mul__(self, other):
        return self._bin("*", other)

    def __rmul__(self, other):
        return self._bin("*", other, flip=True)

    def __truediv__(self, other):
        return self._bin("/", other)

    def __mod__(self, other):
        return self._bin("%", other)

    def __and__(self, other):
        return self._bin("and", other)

    def __or__(self, other):
        return self._bin("or", other)

    def __invert__(self):
        return UnaryOp("not", self)

    def __neg__(self):
        return UnaryOp("neg", self)

    def __hash__(self):
        return id(self)

    def alias(self, name: str) -> "Aliased":
        return Aliased(self, name)


@dataclasses.dataclass(eq=False)
class Col(Expression):
    name: str

    def eval(self, batch: Batch) -> np.ndarray:
        try:
            return batch[self.name]
        except KeyError:
            raise KeyError(
                f"column {self.name!r} not in batch (have "
                f"{sorted(batch)})") from None

    def fields(self) -> set:
        return {self.name}

    def __repr__(self):
        return f"col({self.name!r})"


@dataclasses.dataclass(eq=False)
class Lit(Expression):
    value: Any

    def eval(self, batch: Batch) -> np.ndarray:
        return self.value

    def __repr__(self):
        return f"lit({self.value!r})"


_BIN_FNS: Dict[str, Callable] = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "and": lambda a, b: np.logical_and(a, b),
    "or": lambda a, b: np.logical_or(a, b),
}


@dataclasses.dataclass(eq=False)
class BinOp(Expression):
    op: str
    left: Expression
    right: Expression

    def eval(self, batch: Batch) -> np.ndarray:
        return _BIN_FNS[self.op](self.left.eval(batch), self.right.eval(batch))

    def fields(self) -> set:
        return self.left.fields() | self.right.fields()

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


@dataclasses.dataclass(eq=False)
class UnaryOp(Expression):
    op: str
    arg: Expression

    def eval(self, batch: Batch) -> np.ndarray:
        v = self.arg.eval(batch)
        return np.logical_not(v) if self.op == "not" else -v

    def fields(self) -> set:
        return self.arg.fields()


@dataclasses.dataclass(eq=False)
class Aliased(Expression):
    expr: Expression
    name: str

    def eval(self, batch: Batch) -> np.ndarray:
        return self.expr.eval(batch)

    def fields(self) -> set:
        return self.expr.fields()


def col(name: str) -> Col:
    return Col(name)


def lit(value: Any) -> Lit:
    return Lit(value)
