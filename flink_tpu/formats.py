"""Record formats: bytes ⇄ columnar batches.

ref: flink-formats/* (csv/json (de)serialization schemas —
``DeserializationSchema``/``SerializationSchema``, SURVEY §3.9) and the
format half of flink-connector-files. TPU-first shape: a format's unit
of work is a COLUMN BATCH, not a record — deserialization parses a
whole block of lines into fixed-dtype numpy columns in one pass (the
native C codec when every column is i64/f32 — SURVEY §3.10 item 2),
because per-record Python objects never touch the device path.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Format", "CsvFormat", "JsonLinesFormat"]

Batch = Dict[str, np.ndarray]


class Format:
    """(De)serialization schema seam. ``fields`` names the columns in
    order; deserialize parses a text block; serialize renders a batch
    back to bytes (the sink half).

    ``binary``: False for line-framed text formats (a file of them can
    be split on newlines — FileSource's batching unit); True for
    self-framing binary formats (the columnar format in
    ``formats_columnar.py``), which FileSource must hand the raw file
    image and let the format iterate its own record blocks."""

    fields: Tuple[str, ...]
    binary = False

    def deserialize(self, data: bytes) -> Batch:  # pragma: no cover
        raise NotImplementedError

    def serialize(self, batch: Batch) -> bytes:  # pragma: no cover
        raise NotImplementedError


_DTYPES = {"i64": np.int64, "f32": np.float32, "str": object}


@dataclasses.dataclass(frozen=True)
class CsvFormat(Format):
    """Delimited text ⇄ typed columns. ``schema`` is an ordered mapping
    of column name → 'i64' | 'f32' | 'str'. All-i64 and all-f32 schemas
    take the native single-pass parser; mixed schemas parse per column
    in numpy (ref: flink-formats/flink-csv CsvRowDataDeserializationSchema)."""

    schema: Tuple[Tuple[str, str], ...]
    delimiter: str = ","

    def __init__(self, schema, delimiter: str = ",") -> None:
        object.__setattr__(self, "schema",
                           tuple((n, t) for n, t in schema))
        object.__setattr__(self, "delimiter", delimiter)
        for _, t in self.schema:
            if t not in _DTYPES:
                raise ValueError(f"unknown column type {t!r} "
                                 f"(i64/f32/str)")

    @property
    def fields(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.schema)

    def deserialize(self, data: bytes) -> Batch:
        from flink_tpu import native_codec

        types = [t for _, t in self.schema]
        names = [n for n, _ in self.schema]
        ncols = len(names)
        if all(t == "i64" for t in types):
            table = native_codec.parse_i64_table(
                data, ncols, delim=self.delimiter)
            return {n: table[:, i].copy() for i, n in enumerate(names)}
        if all(t == "f32" for t in types):
            table = native_codec.parse_f32_table(
                data, ncols, delim=self.delimiter)
            return {n: table[:, i].copy() for i, n in enumerate(names)}
        rows = [ln.split(self.delimiter)
                for ln in data.decode("utf-8").splitlines() if ln]
        out: Batch = {}
        for i, (n, t) in enumerate(self.schema):
            col = [r[i] if i < len(r) else "" for r in rows]
            if t == "i64":
                out[n] = np.array([int(c or 0) for c in col], np.int64)
            elif t == "f32":
                out[n] = np.array([float(c or 0) for c in col], np.float32)
            else:
                out[n] = np.array(col, dtype=object)
        return out

    def serialize(self, batch: Batch) -> bytes:
        from flink_tpu import native_codec

        names = self.fields
        n = len(batch[names[0]]) if names else 0
        types = [t for _, t in self.schema]
        if all(t == "i64" for t in types):
            table = np.stack(
                [np.asarray(batch[c], np.int64) for c in names], axis=1)
            return native_codec.encode_i64_rows(table, self.delimiter)
        cols = [batch[c] for c in names]
        lines = []
        for i in range(n):
            lines.append(self.delimiter.join(
                str(col[i]) for col in cols))
        return ("\n".join(lines) + ("\n" if lines else "")).encode()


@dataclasses.dataclass(frozen=True)
class JsonLinesFormat(Format):
    """One JSON object per line ⇄ columns (ref: flink-formats/
    flink-json JsonRowDataDeserializationSchema). ``schema`` as in
    CsvFormat; missing keys fill the type's zero."""

    schema: Tuple[Tuple[str, str], ...]

    def __init__(self, schema) -> None:
        object.__setattr__(self, "schema",
                           tuple((n, t) for n, t in schema))

    @property
    def fields(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.schema)

    def deserialize(self, data: bytes) -> Batch:
        objs = [json.loads(ln) for ln in data.splitlines() if ln.strip()]
        out: Batch = {}
        for n, t in self.schema:
            if t == "i64":
                out[n] = np.array([int(o.get(n, 0)) for o in objs],
                                  np.int64)
            elif t == "f32":
                out[n] = np.array([float(o.get(n, 0.0)) for o in objs],
                                  np.float32)
            else:
                out[n] = np.array([str(o.get(n, "")) for o in objs],
                                  dtype=object)
        return out

    def serialize(self, batch: Batch) -> bytes:
        names = self.fields
        n = len(batch[names[0]]) if names else 0
        lines = []
        for i in range(n):
            row = {}
            for name, t in self.schema:
                v = batch[name][i]
                row[name] = (int(v) if t == "i64"
                             else float(v) if t == "f32" else str(v))
            lines.append(json.dumps(row))
        return ("\n".join(lines) + ("\n" if lines else "")).encode()
