"""Disk-backed LSM keyed-state tier: larger-than-memory exact windows.

``HostSpillStore`` (state/spill.py) degrades keys beyond HBM to host
RAM — but every spilled byte is still RAM-resident, so key domains
beyond host memory kill the job. This module is the RocksDB state
backend + flink-dstl changelog analogue (SURVEY §3.4): the same
per-(key, pane) monoid accumulators, tiered to disk.

Shape (classic LSM, specialized to monoid lanes):

- **delta (memtable)**: an internal ``HostSpillStore`` absorbs batches
  exactly as the RAM backend does, bounded by
  ``state.memory-budget-bytes``.
- **seal**: past budget the delta's pane tables flatten into one
  SORTED run — ``(pane, key)``-ordered rows with a key-group (shard)
  column — written in the CRC'd ``formats_columnar`` segment format
  (``run-<seq>.seg``), tmp + sync + rename, then the store manifest
  (``MANIFEST.json``, the atomic visibility point) publishes it via
  ``fs.write_atomic``. CrashFS covers the tier because every durable
  byte rides the fs.py seam.
- **fire**: pane-range-pruned runs decode zero-copy off mmap and
  monoid-merge with the delta — runs in seal order, delta last, so
  float lane sums keep the exact left-fold order of the un-spilled
  store: **byte-identical output across a spill/no-spill config
  flip**, the tier's core invariant.
- **compact**: at ``state.lsm.compact-min-runs`` live runs, a leveled
  pass folds them (same seal-order fold) into one higher-level run
  under the bus tier's ``maintenance_pass`` lock discipline
  (log/bus.py) — manifest swap is the visibility point
  (``state.compact.swap``), replaced files become sweepable debris.
  Pre-folding runs left-to-right preserves the fire-time fold order,
  so compaction never changes fired bytes either.
- **changelog checkpoints**: ``snapshot()`` inlines only the delta and
  NAMES the sealed runs (``aux_files``); the checkpoint plane
  hardlinks those immutable files (``checkpoint/storage.py`` op_aux,
  ``state.changelog.link``) — checkpoint cost scales with write rate,
  not state size. ``restore`` links runs back and replays the delta;
  it also accepts a plain ``HostSpillStore`` snapshot (a
  spill→lsm backend flip restores cleanly).
- **rescale**: every run row carries its key-group shard, so
  ``checkpoint/repartition.py`` re-slices the tier by filtering rows
  to the new process's shard range — no "spilled state refuses to
  rescale" residue for this backend.

Debris discipline: compaction/purge never unlink replaced run files
inline — a checkpoint freeze may have NAMED them for a hardlink still
in flight on the checkpoint executor. Replaced files queue on a
pending list swept at the NEXT maintenance/seal pass (at least one
full budget-fill later); if a persist ever outlives that grace the
link fails LOUDLY (ENOENT → failed checkpoint, tolerable-failures
path), never silently. fsck treats unreferenced run/tmp files as
repairable debris for the same reason.

Honest scope (COMPONENTS.md): one store per operator instance on ONE
host; local filesystem only (runs are mmap'd); no bloom filters or
block cache — fires prune runs by pane range, not key.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu import faults
from flink_tpu.formats_columnar import (ColumnarError, ColumnarWriter,
                                        iter_blocks, map_file_image)
from flink_tpu.fs import get_filesystem, open_write_sync, write_atomic
from flink_tpu.state.spill import HostSpillStore


def _run_image(path: str):
    """Sealed-run bytes: mmapped straight off the page cache on a
    plain local path, read through the fs layer on any scheme'd one
    (file://, crash:// — CrashFS must see the read route)."""
    if "://" not in path:
        return map_file_image(path)
    with get_filesystem(path).open_read(path) as f:
        data = f.read()
    return data if isinstance(data, bytes) else data.encode("utf-8")

MANIFEST = "MANIFEST.json"
_BASE_SCHEMA = (("shard", "i64"), ("key", "i64"), ("pane", "i64"),
                ("count", "i64"))


def run_schema(sum_width: int, max_width: int,
               min_width: int) -> Tuple[Tuple[str, str], ...]:
    """Run-file schema for an aggregate's lane widths: base columns +
    one f32 column per sum/max/min lane (s0.., x0.., n0..)."""
    lanes = ([(f"s{i}", "f32") for i in range(sum_width)]
             + [(f"x{i}", "f32") for i in range(max_width)]
             + [(f"n{i}", "f32") for i in range(min_width)])
    return _BASE_SCHEMA + tuple(lanes)


class LsmSpillStore:
    """Spill-store-compatible disk tier (duck-types ``HostSpillStore``:
    absorb / fire / purge_below / snapshot / restore / bytes_used /
    key_count / records_spilled). Constructed by ops/factory.py when
    ``state.backend = 'lsm'``."""

    def __init__(self, agg, *, store_dir: str,
                 memory_budget_bytes: int,
                 num_shards: int = 128,
                 compact_min_runs: int = 4,
                 pool=None,
                 fold_chunk_records: Optional[int] = None):
        self.agg = agg
        self.dir = str(store_dir)
        self.memory_budget_bytes = int(memory_budget_bytes)
        self.num_shards = int(num_shards)
        self.compact_min_runs = max(2, int(compact_min_runs))
        self._fs = get_filesystem(self.dir)
        self._delta = HostSpillStore(
            agg, pool=pool, fold_chunk_records=fold_chunk_records)
        self.schema = run_schema(agg.sum_width, agg.max_width,
                                 agg.min_width)
        self._runs: List[Dict[str, Any]] = []  # manifest order = seq order
        self._seq = 0        # monotone file-name counter (seals + compacts)
        self._gen = 0        # manifest generation (visibility swaps)
        self._floor = 0      # purge floor: panes below are dead
        self._pending_delete: List[str] = []  # replaced runs, grace-swept
        self.seals = 0
        self.compactions = 0
        self._open()

    # -- store directory lifecycle ---------------------------------------

    def _open(self) -> None:
        """Adopt an existing store directory (warm restart: manifest is
        the truth) or initialize a fresh one; either way sweep debris
        the manifest does not reference (crashed seal/compact tmp and
        pre-swap output — the log-tier orphan discipline)."""
        self._fs.mkdirs(self.dir)
        mpath = os.path.join(self.dir, MANIFEST)
        if self._fs.exists(mpath):
            with self._fs.open_read(mpath) as f:
                man = json.loads(f.read().decode("utf-8"))
            if man.get("format") != "lsm-state":
                raise ValueError(
                    f"{mpath} is not an lsm-state manifest "
                    f"(format={man.get('format')!r})")
            self._runs = [dict(r) for r in man.get("runs", [])]
            self._seq = int(man.get("seq", 0))
            self._gen = int(man.get("gen", 0))
            self._floor = int(man.get("purged_below", 0))
        self._sweep_orphans()

    def _sweep_orphans(self) -> None:
        live = {r["name"] for r in self._runs}
        for name in self._fs.listdir(self.dir):
            if name.endswith(".tmp") or (
                    name.startswith("run-") and name.endswith(".seg")
                    and name not in live):
                try:
                    self._fs.delete(os.path.join(self.dir, name))
                except OSError:
                    pass  # debris removal is best-effort; fsck re-flags

    def _write_manifest(self) -> None:
        self._gen += 1
        payload = json.dumps({
            "format": "lsm-state", "v": 1, "gen": self._gen,
            "seq": self._seq, "purged_below": self._floor,
            "num_shards": self.num_shards,
            "runs": self._runs,
        }, separators=(",", ":")).encode("utf-8")
        write_atomic(self._fs, os.path.join(self.dir, MANIFEST), payload)

    def _sweep_pending(self) -> None:
        """Unlink runs replaced a full pass ago (see module docstring:
        the checkpoint-link grace — never inline with the swap)."""
        pending, self._pending_delete = self._pending_delete, []
        for name in pending:
            try:
                self._fs.delete(os.path.join(self.dir, name))
            except OSError:
                self._pending_delete.append(name)  # retry next pass

    # -- ingest ----------------------------------------------------------

    def absorb(self, keys: np.ndarray, panes: np.ndarray,
               data: Dict[str, np.ndarray]) -> None:
        self._delta.absorb(keys, panes, data)
        self._maybe_seal()

    def _maybe_seal(self) -> None:
        if not self._delta.panes:
            return
        if self._delta.bytes_used() > self.memory_budget_bytes:
            self._seal_delta()
            if len(self._runs) >= self.compact_min_runs:
                self.compact()

    def _rows_from_tables(
            self, tables: Dict[int, Tuple[np.ndarray, ...]]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Pane tables → (pane, key)-sorted run rows with the key-group
        shard column (the rescale address)."""
        if not tables:
            return None
        from flink_tpu.exchange.partitioners import hash_shards

        S, M, m = (self.agg.sum_width, self.agg.max_width,
                   self.agg.min_width)
        kk, pp, ss, xx, nn, cc = [], [], [], [], [], []
        for p in sorted(tables):
            k, s, x, n, c = tables[p]
            kk.append(np.asarray(k, np.int64))
            pp.append(np.full(len(k), p, np.int64))
            ss.append(s)
            xx.append(x)
            nn.append(n)
            cc.append(np.asarray(c, np.int64))
        key = np.concatenate(kk)
        pane = np.concatenate(pp)
        # panes already pane-major and key-sorted within (HostSpillStore
        # pane keys are sorted unions), so rows are (pane, key)-ordered
        cols: Dict[str, np.ndarray] = {
            "shard": hash_shards(key, self.num_shards),
            "key": key, "pane": pane,
            "count": np.concatenate(cc),
        }
        sums = np.concatenate(ss)
        maxs = np.concatenate(xx)
        mins = np.concatenate(nn)
        for i in range(S):
            cols[f"s{i}"] = np.ascontiguousarray(sums[:, i])
        for i in range(M):
            cols[f"x{i}"] = np.ascontiguousarray(maxs[:, i])
        for i in range(m):
            cols[f"n{i}"] = np.ascontiguousarray(mins[:, i])
        return cols

    def _write_run(self, cols: Dict[str, np.ndarray], level: int,
                   fsync_point: Optional[str] = None) -> Dict[str, Any]:
        """Durable run write: tmp + close-time sync + rename + dir
        barrier. The manifest (NOT this file's existence) is what makes
        a run live — a crash here leaves sweepable debris only."""
        self._seq += 1
        name = f"run-{self._seq:06d}.seg"
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open_write_sync(self._fs, tmp, sync=True) as f:
            w = ColumnarWriter(f, self.schema)
            w.write_batch(cols)
            if fsync_point:
                # the barrier seam: bytes staged, durability pending
                faults.fire(fsync_point, exc=OSError, run=name)
            w.close()
        self._fs.rename(tmp, path)
        self._fs.fsync(self.dir)  # the rename's directory entry
        pane = cols["pane"]
        shard = cols["shard"]
        return {
            "name": name, "level": int(level), "seq": self._seq,
            "rows": int(len(pane)),
            "min_pane": int(pane.min()), "max_pane": int(pane.max()),
            "shard_lo": int(shard.min()), "shard_hi": int(shard.max()),
            "bytes": self._fs.size(path),
        }

    def _seal_delta(self) -> None:
        cols = self._rows_from_tables(self._delta.panes)
        if cols is None:
            return
        faults.fire("state.run.seal", exc=OSError, store=self.dir)
        meta = self._write_run(cols, level=0,
                               fsync_point="state.run.fsync")
        self._runs.append(meta)
        self._write_manifest()  # visibility point: run is live
        spilled = self._delta.records_spilled
        self._delta.panes = {}
        self._delta._pane_locks = {}
        self._delta.records_spilled = spilled  # lifetime count survives
        self.seals += 1
        self._sweep_pending()

    # -- run reads -------------------------------------------------------

    def _run_tables(self, meta: Dict[str, Any],
                    pane_lo: Optional[int] = None,
                    pane_hi: Optional[int] = None
                    ) -> Dict[int, Tuple[np.ndarray, ...]]:
        """Decode one run (zero-copy off mmap) into pane tables,
        optionally restricted to panes in [pane_lo, pane_hi) and always
        excluding panes below the purge floor."""
        S, M, m = (self.agg.sum_width, self.agg.max_width,
                   self.agg.min_width)
        image = _run_image(os.path.join(self.dir, meta["name"]))
        out: Dict[int, Tuple[np.ndarray, ...]] = {}
        for block in iter_blocks(image, expect_schema=self.schema,
                                 zero_copy=True):
            pane = block["pane"]
            mask = pane >= self._floor
            if pane_lo is not None:
                mask &= (pane >= pane_lo) & (pane < pane_hi)
            if not mask.any():
                continue
            pane = pane[mask]
            key = block["key"][mask]
            cnt = block["count"][mask]
            sums = (np.stack([block[f"s{i}"][mask] for i in range(S)],
                             axis=1) if S else
                    np.zeros((len(key), 0), np.float32))
            maxs = (np.stack([block[f"x{i}"][mask] for i in range(M)],
                             axis=1) if M else
                    np.zeros((len(key), 0), np.float32))
            mins = (np.stack([block[f"n{i}"][mask] for i in range(m)],
                             axis=1) if m else
                    np.zeros((len(key), 0), np.float32))
            # rows are (pane, key)-sorted: pane groups are contiguous
            # and keys arrive sorted within each — exactly the pane-
            # table invariant _merge_pane/_fire_window rely on
            bounds = np.flatnonzero(np.concatenate(
                [[True], pane[1:] != pane[:-1], [True]]))
            for i in range(len(bounds) - 1):
                a, b = int(bounds[i]), int(bounds[i + 1])
                p = int(pane[a])
                piece = (key[a:b], sums[a:b], maxs[a:b], mins[a:b],
                         cnt[a:b])
                if p in out:  # same pane split across blocks
                    got = out[p]
                    tmp = HostSpillStore(self.agg)
                    tmp.panes[p] = got
                    tmp._merge_pane(p, *piece)
                    out[p] = tmp.panes[p]
                else:
                    out[p] = piece
        return out

    def _fold_runs(self, runs: List[Dict[str, Any]],
                   pane_lo: Optional[int] = None,
                   pane_hi: Optional[int] = None,
                   include_delta: bool = False) -> HostSpillStore:
        """Monoid-fold runs (seal order) and optionally the delta
        (LAST) into a scratch store — the one fold order everything
        (fire, compact, rescale) shares, so float lane sums are
        bit-stable across tiering decisions."""
        scratch = HostSpillStore(self.agg)
        for meta in runs:
            for p, piece in self._run_tables(
                    meta, pane_lo, pane_hi).items():
                scratch._merge_pane(p, *piece)
        if include_delta:
            for p, (k, s, x, n, c) in self._delta.panes.items():
                if p < self._floor:
                    continue
                if pane_lo is not None and not (pane_lo <= p < pane_hi):
                    continue
                scratch._merge_pane(p, k, s, x, n, c)
        return scratch

    def _live_runs(self, pane_lo: Optional[int] = None,
                   pane_hi: Optional[int] = None) -> List[Dict[str, Any]]:
        out = []
        for meta in self._runs:
            if meta["max_pane"] < self._floor:
                continue
            if pane_lo is not None and (meta["max_pane"] < pane_lo
                                        or meta["min_pane"] >= pane_hi):
                continue
            out.append(meta)
        return out

    # -- fire ------------------------------------------------------------

    def fire(self, ends: List[int], panes_per_window: int, pane_ms: int,
             offset_ms: int, size_ms: int
             ) -> Optional[Dict[str, np.ndarray]]:
        if not ends:
            return None
        if not self._runs:  # pure-RAM fast path: exact delta semantics
            return self._delta.fire(ends, panes_per_window, pane_ms,
                                    offset_ms, size_ms)
        ppw = panes_per_window
        pane_lo = min(ends) - ppw
        pane_hi = max(ends)
        runs = self._live_runs(pane_lo, pane_hi)
        scratch = self._fold_runs(runs, pane_lo, pane_hi,
                                  include_delta=True)
        return scratch.fire(ends, ppw, pane_ms, offset_ms, size_ms)

    # -- maintenance -----------------------------------------------------

    def compact(self) -> bool:
        """Leveled compaction: fold EVERY live run (seal order — the
        fire-time fold prefix, so fired bytes never change) into one
        run at level max+1, publish by manifest swap, queue replaced
        files for the grace sweep. Serialized per store by the bus
        tier's maintenance lock. Returns False when another pass holds
        the lock (skip, retry at the next seal)."""
        from flink_tpu.log.bus import LogError, maintenance_pass

        live = self._live_runs()
        if len(live) < 2:
            return False
        try:
            with maintenance_pass(self.dir):
                self._sweep_pending()
                scratch = self._fold_runs(live)
                cols = self._rows_from_tables(scratch.panes)
                replaced = [r["name"] for r in live]
                if cols is None:
                    self._runs = [r for r in self._runs
                                  if r["name"] not in replaced]
                else:
                    level = max(r["level"] for r in live) + 1
                    meta = self._write_run(cols, level=level)
                    self._runs = [r for r in self._runs
                                  if r["name"] not in replaced] + [meta]
                faults.fire("state.compact.swap", exc=OSError,
                            store=self.dir)
                self._write_manifest()  # visibility point (the swap)
                self._pending_delete.extend(replaced)
                self.compactions += 1
                return True
        except LogError:
            return False

    def purge_below(self, dead_pane: int) -> None:
        self._delta.purge_below(dead_pane)
        if dead_pane <= self._floor:
            return
        self._floor = int(dead_pane)
        dead = [r for r in self._runs if r["max_pane"] < self._floor]
        if not dead:
            # the floor itself persists lazily (next seal/compact swap
            # carries it) — a stale floor after warm restart only
            # retains a few dead panes, it can never refire them, and
            # purge runs per watermark advance: an fsync here would tax
            # the hot path for no correctness gain
            return
        names = {r["name"] for r in dead}
        self._runs = [r for r in self._runs if r["name"] not in names]
        self._write_manifest()
        self._pending_delete.extend(r["name"] for r in dead)

    # -- accounting ------------------------------------------------------

    @property
    def records_spilled(self) -> int:
        return self._delta.records_spilled

    @records_spilled.setter
    def records_spilled(self, v: int) -> None:
        self._delta.records_spilled = int(v)

    def bytes_used(self) -> int:
        """Delta RAM + sealed run bytes (the tier's full footprint)."""
        return self._delta.bytes_used() + sum(
            int(r["bytes"]) for r in self._runs)

    def delta_bytes(self) -> int:
        return self._delta.bytes_used()

    @property
    def run_count(self) -> int:
        return len(self._runs)

    @property
    def key_count(self) -> int:
        ks = [t[0] for t in self._delta.panes.values()]
        for meta in self._live_runs():
            ks.extend(t[0] for t in self._run_tables(meta).values())
        if not ks:
            return 0
        return len(np.unique(np.concatenate(ks)))

    # -- checkpoint / restore --------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The CHANGELOG cut: inline delta + run NAMES. ``aux_files``
        is the contract with the checkpoint plane — the operator lifts
        it to ``__aux_files__`` and storage.save_v2 hardlinks each
        (immutable, already-durable) run into the checkpoint directory
        instead of re-serializing state, so checkpoint bytes track the
        write rate, not the key domain."""
        return {
            "kind": "lsm",
            "delta": self._delta.snapshot(),
            "runs": [dict(r) for r in self._runs],
            "seq": self._seq,
            "purged_below": self._floor,
            "num_shards": self.num_shards,
            "records_spilled": self._delta.records_spilled,
            "aux_files": {r["name"]: os.path.join(self.dir, r["name"])
                          for r in self._runs},
        }

    def restore(self, snap: Optional[Dict[str, Any]],
                aux_paths: Optional[Dict[str, str]] = None) -> None:
        """Rebuild the tier from a snapshot. Accepts the lsm form
        (delta + named runs; ``aux_paths`` maps run name → source file,
        normally the checkpoint directory's hardlinks) or a plain
        ``HostSpillStore`` snapshot (``{"panes": ...}``) so a job may
        flip state.backend spill→lsm across a restore."""
        self._runs = []
        self._pending_delete = []
        if snap is None:
            self._delta.panes = {}
            self._delta.records_spilled = 0
            self._floor = 0
            self._write_manifest()
            self._sweep_orphans()
            return
        if snap.get("kind") != "lsm":
            self._delta.restore(snap)  # RAM-spill snapshot adoption
            self._floor = 0
            self._write_manifest()
            self._sweep_orphans()
            self._maybe_seal()
            return
        self._delta.restore(snap["delta"])
        self._delta.records_spilled = int(snap.get(
            "records_spilled", self._delta.records_spilled))
        self._floor = int(snap.get("purged_below", 0))
        self._seq = max(self._seq, int(snap.get("seq", 0)))
        aux = dict(snap.get("aux_files") or {})
        aux.update(aux_paths or {})
        for meta in snap.get("runs", []):
            meta = dict(meta)
            name = meta["name"]
            dst = os.path.join(self.dir, name)
            src = aux.get(name)
            if src and os.path.abspath(src) != os.path.abspath(dst):
                self._fs.link_or_copy(src, dst)
            elif not self._fs.exists(dst):
                raise ValueError(
                    f"lsm restore: run {name!r} named by the snapshot "
                    f"has no source (no aux path, not in {self.dir}) — "
                    "restore from the checkpoint directory that owns "
                    "the changelog files")
            self._runs.append(meta)
        self._fs.fsync(self.dir)
        self._write_manifest()
        self._sweep_orphans()
        self._maybe_seal()


# -- rescale (checkpoint/repartition.py) -----------------------------------

class _LaneWidths:
    """Width-only aggregate shim: the scratch merge below needs the
    lane contract's widths and nothing else of the aggregate."""

    def __init__(self, sum_width: int, max_width: int,
                 min_width: int) -> None:
        self.sum_width = sum_width
        self.max_width = max_width
        self.min_width = min_width


def _decode_run_panes(path: str, floor: int
                      ) -> List[Tuple[int, Tuple[np.ndarray, ...]]]:
    """Decode a run file into per-pane ``(keys, sums, maxs, mins,
    counts, shards)`` tuples using the lane widths recorded in its OWN
    schema — rescale runs in a tool/merge process that has no
    aggregate object to ask."""
    from flink_tpu.formats_columnar import read_schema

    image = _run_image(path)
    names = [n for n, _ in read_schema(image)]
    S = sum(1 for n in names if n[0] == "s" and n[1:].isdigit())
    M = sum(1 for n in names if n[0] == "x" and n[1:].isdigit())
    m = sum(1 for n in names if n[0] == "n" and n[1:].isdigit())
    out: List[Tuple[int, Tuple[np.ndarray, ...]]] = []
    for block in iter_blocks(image, zero_copy=True):
        pane = np.asarray(block["pane"])
        mask = pane >= floor
        if not mask.any():
            continue
        pane = pane[mask]
        key = np.asarray(block["key"])[mask]
        shard = np.asarray(block["shard"])[mask]
        cnt = np.asarray(block["count"])[mask]
        sums = (np.stack([np.asarray(block[f"s{i}"])[mask]
                          for i in range(S)], axis=1) if S else
                np.zeros((len(key), 0), np.float32))
        maxs = (np.stack([np.asarray(block[f"x{i}"])[mask]
                          for i in range(M)], axis=1) if M else
                np.zeros((len(key), 0), np.float32))
        mins = (np.stack([np.asarray(block[f"n{i}"])[mask]
                          for i in range(m)], axis=1) if m else
                np.zeros((len(key), 0), np.float32))
        bounds = np.flatnonzero(np.concatenate(
            [[True], pane[1:] != pane[:-1], [True]]))
        for i in range(len(bounds) - 1):
            a, b = int(bounds[i]), int(bounds[i + 1])
            out.append((int(pane[a]),
                        (key[a:b], sums[a:b], maxs[a:b], mins[a:b],
                         cnt[a:b], shard[a:b])))
    return out


def merge_rescale_spill(parts, *, num_shards: int, shard_lo: int,
                        shard_hi: int) -> Dict[str, Any]:
    """Key-group repartition of lsm spill snapshots — the reason run
    rows carry a shard column.

    ``parts``: one ``(spill_snapshot, aux_paths)`` pair per OLD process
    in old-pid order (``aux_paths`` maps run name → file path, the
    savepoint's changelog hardlinks; ``None`` entries are processes
    with no lsm spill). Each process's state folds in the store's ONE
    fold order — runs in seal order, delta last — keeping only rows
    whose key-group lands in ``[shard_lo, shard_hi)``; run rows filter
    by their stored shard column, delta keys re-hash. Old processes
    own disjoint key sets, so the cross-process fold order cannot
    change any float lane.

    Returns a PURE-DELTA lsm snapshot (no runs): the restoring store
    re-seals under its own budget, so no run file crosses the cut and
    the merged payload stays self-contained.
    """
    from flink_tpu.exchange.partitioners import hash_shards

    scratch: Optional[HostSpillStore] = None
    records = 0
    floors: List[int] = []

    def _scr(s: np.ndarray, x: np.ndarray, n: np.ndarray
             ) -> HostSpillStore:
        nonlocal scratch
        if scratch is None:
            scratch = HostSpillStore(_LaneWidths(
                s.shape[1], x.shape[1], n.shape[1]))
        return scratch

    for snap, aux in parts:
        if not snap:
            continue
        floor = int(snap.get("purged_below", 0))
        floors.append(floor)
        records += int(snap.get("records_spilled", 0))
        for meta in snap.get("runs", []):
            path = (aux or {}).get(meta["name"])
            if path is None:
                raise ValueError(
                    f"lsm rescale: run {meta['name']!r} named by the "
                    "snapshot has no aux path — merge savepoints "
                    "written by the changelog plane (save_v2), whose "
                    "manifests carry the run hardlinks")
            for p, (k, s, x, n, c, sh) in _decode_run_panes(path, floor):
                keep = (sh >= shard_lo) & (sh < shard_hi)
                if not keep.any():
                    continue
                _scr(s, x, n)._merge_pane(
                    p, k[keep], s[keep], x[keep], n[keep], c[keep])
        delta = snap.get("delta") or {}
        for p, tab in (delta.get("panes") or {}).items():
            p = int(p)
            if p < floor:
                continue
            k, s, x, n, c = (np.asarray(a) for a in tab)
            sh = hash_shards(np.asarray(k, np.int64), num_shards)
            keep = (sh >= shard_lo) & (sh < shard_hi)
            if not keep.any():
                continue
            _scr(s, x, n)._merge_pane(
                p, k[keep], s[keep], x[keep], n[keep], c[keep])
    panes = ({} if scratch is None
             else {int(p): t for p, t in scratch.panes.items()})
    return {
        "kind": "lsm",
        "delta": {"panes": panes, "records_spilled": records},
        "runs": [], "seq": 0,
        "purged_below": min(floors) if floors else 0,
        "num_shards": num_shards,
        "records_spilled": records,
    }
