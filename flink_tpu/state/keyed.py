"""Keyed state: dense HBM pane tensors + host key directory.

This is the HeapKeyedStateBackend replacement (ref: flink-runtime/.../
runtime/state/heap/{HeapKeyedStateBackend,CopyOnWriteStateTable,
CopyOnWriteStateMap}.java — a per-record nested-hash-map probe), redesigned
for TPU: state lives as dense ``(slots, panes, width)`` accumulator
tensors in HBM so a whole microbatch folds in with three scatters, and the
hash-map role (key → state address) moves to a **host-side directory**
that assigns each distinct key a stable slot inside its key shard.

Key shards (ref: runtime/state/KeyGroupRangeAssignment.java — key groups,
default max-parallelism 128) decouple the logical key space from physical
devices: shard = splitmix64(key) % num_shards; a device owns a contiguous
shard range; global slot = shard * slots_per_shard + local index. Rescale
= re-assign shard ranges (checkpoint/reshard reads this layout).

Copy-on-write snapshot isolation comes free: jax arrays are immutable, so
a checkpoint simply keeps a reference to the state pytree of a step
boundary while processing continues on new arrays (the CopyOnWriteStateTable
role collapses into XLA donation semantics).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.records import hash_keys_numpy


@dataclasses.dataclass(frozen=True)
class PaneStateLayout:
    """Static shape of one window-operator state family (per device shard
    range when sharded; ``slots`` is the LOCAL slot count).

    One extra "dump" row at index ``slots`` swallows scatters from
    padding rows — branchless masking, no dynamic shapes.
    """

    slots: int          # local key capacity (num_local_shards * slots_per_shard)
    ring: int           # pane ring length (>= live pane span, see plan())
    sum_width: int
    max_width: int
    min_width: int

    @property
    def rows(self) -> int:
        return self.slots + 1  # + dump row

    def bytes(self) -> int:
        per_cell = 4 * (self.sum_width + self.max_width + self.min_width) + 4
        return self.rows * self.ring * per_cell


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PaneState:
    """Device-resident accumulator tensors. counts is always present (it
    is the COUNT lane, the trigger-count source, and the non-empty mask).

    Zero-width lane families are ``None``, NOT zero-size arrays: None is
    an empty pytree, so jit in/out carries no buffer for them. A
    zero-size runtime buffer is not free on every backend — on the
    remote-attached TPU each one added ~27ms of per-step stream stall
    (measured round 4: count-only apply 84.6ms/step with three (rows,
    ring, 0) lanes vs 3.3ms without)."""

    sums: Optional[jax.Array]   # (rows, ring, sum_width) f32, None if width 0
    maxs: Optional[jax.Array]   # (rows, ring, max_width) f32, None if width 0
    mins: Optional[jax.Array]   # (rows, ring, min_width) f32, None if width 0
    counts: jax.Array  # (rows, ring) i32

    def tree_flatten(self):
        return (self.sums, self.maxs, self.mins, self.counts), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def init_state(layout: PaneStateLayout) -> PaneState:
    def lane(width: int, fill: float) -> Optional[jax.Array]:
        if width == 0:
            return None
        return jnp.full((layout.rows, layout.ring, width), fill, jnp.float32)

    return PaneState(
        sums=lane(layout.sum_width, 0.0),
        maxs=lane(layout.max_width, -float("inf")),
        mins=lane(layout.min_width, float("inf")),
        counts=jnp.zeros((layout.rows, layout.ring), jnp.int32),
    )


class _NumpyHashTable:
    """Open-addressing int64→int64 map with fully vectorized batch
    lookup AND batch insert/update (linear probing; load factor kept
    ≤ 0.5 by doubling) — key churn costs numpy probe rounds, never a
    Python loop per key."""

    def __init__(self, capacity_hint: int = 1024) -> None:
        size = 1
        while size < max(capacity_hint * 2, 16):
            size *= 2
        self._keys = np.zeros(size, dtype=np.int64)
        self._vals = np.zeros(size, dtype=np.int64)
        self._used = np.zeros(size, dtype=bool)
        self._count = 0

    def lookup_keys(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        return self.lookup(keys, hash_keys_numpy(keys))

    def lookup(self, keys: np.ndarray, key_hashes: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(values, found) for a batch. Vectorized probe: each round
        resolves every query that hits its key or an empty bucket."""
        mask = len(self._keys) - 1
        ix = (key_hashes & mask).astype(np.int64)
        out = np.full(len(keys), -1, dtype=np.int64)
        found = np.zeros(len(keys), dtype=bool)
        pending = np.arange(len(keys))
        for _ in range(len(self._keys)):
            if len(pending) == 0:
                break
            pix = ix[pending]
            hit = self._used[pix] & (self._keys[pix] == keys[pending])
            empty = ~self._used[pix]
            out[pending[hit]] = self._vals[pix[hit]]
            found[pending[hit]] = True
            pending = pending[~hit & ~empty]
            ix[pending] = (ix[pending] + 1) & mask
        return out, found

    def insert(self, key: int, key_hash: int, val: int) -> None:
        self.insert_batch(
            np.asarray([key], np.int64),
            np.asarray([key_hash], np.uint64),
            np.asarray([val], np.int64))

    def insert_batch(self, keys: np.ndarray, key_hashes: np.ndarray,
                     vals: np.ndarray) -> None:
        """Vectorized linear-probe insert for a batch of DISTINCT keys.
        Each probe round settles every query whose bucket holds its key
        (update) or wins an empty bucket (one writer per bucket per
        round); the rest step forward — same round structure as lookup,
        so key churn costs O(rounds) numpy passes, not a Python loop
        per key."""
        n = len(keys)
        if n == 0:
            return
        while (self._count + n) * 2 > len(self._keys):
            self._grow()
        keys = np.asarray(keys, np.int64)
        vals = np.asarray(vals, np.int64)
        mask = len(self._keys) - 1
        ix = (key_hashes & mask).astype(np.int64)
        pending = np.arange(n)
        while len(pending):
            pix = ix[pending]
            used = self._used[pix]
            samekey = used & (self._keys[pix] == keys[pending])
            upd = pending[samekey]
            self._vals[ix[upd]] = vals[upd]
            emp = pending[~used]
            _, first = np.unique(ix[emp], return_index=True)
            win = emp[first]
            self._keys[ix[win]] = keys[win]
            self._vals[ix[win]] = vals[win]
            self._used[ix[win]] = True
            self._count += len(win)
            settled = np.zeros(n, dtype=bool)
            settled[upd] = True
            settled[win] = True
            pending = pending[~settled[pending]]
            ix[pending] = (ix[pending] + 1) & mask

    def _grow(self) -> None:
        old_keys, old_vals, old_used = self._keys, self._vals, self._used
        self.__init__(capacity_hint=len(old_keys))
        live = np.nonzero(old_used)[0]
        if len(live):
            self.insert_batch(
                old_keys[live], hash_keys_numpy(old_keys[live]), old_vals[live])


class KeyDirectory:
    """Host-side key → slot mapping (the hash-map half of the state
    backend; ref role: CopyOnWriteStateMap.get/put, but amortized over a
    batch and off the device hot path).

    Batch lookups are fully vectorized over a numpy open-addressing
    table; only never-before-seen keys take the per-key insert path.
    Slot ids are stable for the life of the job (and across checkpoints —
    the directory is part of the snapshot manifest).
    """

    FULL = -2  # sentinel: shard out of slots (spill backend takes over)

    def __init__(self, num_shards: int, slots_per_shard: int,
                 shard_range: Tuple[int, int] | None = None) -> None:
        self.num_shards = num_shards
        self.slots_per_shard = slots_per_shard
        # shard range owned by this directory (global view: (0, num_shards))
        self.shard_lo, self.shard_hi = shard_range or (0, num_shards)
        # C fast path when the codec library is available (same probe
        # semantics, same splitmix64 hash — parity-tested); numpy
        # otherwise. ~90ms → ~10ms per 2^20-record batch.
        from flink_tpu.native_codec import NativeHashTable

        self._table = NativeHashTable.create() or _NumpyHashTable()
        self._next_free = np.zeros(num_shards, dtype=np.int64)
        n_local = (self.shard_hi - self.shard_lo) * slots_per_shard
        self._rev_keys = np.zeros(n_local, dtype=np.int64)
        self._rev_used = np.zeros(n_local, dtype=bool)

    @property
    def local_slots(self) -> int:
        return (self.shard_hi - self.shard_lo) * self.slots_per_shard

    def shard_of(self, keys: np.ndarray) -> np.ndarray:
        return hash_keys_numpy(np.asarray(keys, dtype=np.int64)) % self.num_shards

    def assign(self, keys: np.ndarray) -> np.ndarray:
        """Map raw int64 keys → LOCAL slot ids (relative to shard_lo).

        Returns -1 where the key's shard is outside this directory's
        range (caller routed wrong) and FULL where the shard is out of
        slots (spill-layer responsibility).
        """
        keys = np.asarray(keys, dtype=np.int64)
        slots, found = self._table.lookup_keys(keys)
        if not found.all():
            miss_ix = np.nonzero(~found)[0]
            # allocate + register each distinct new key once, vectorized
            # (key churn is per-batch steady state in rotating-key
            # workloads like Nexmark; a Python loop here was 60ms/batch);
            # only the DISTINCT misses are hashed on the Python side —
            # the hit path's hashes live inside the table lookup
            uniq, inv = np.unique(keys[miss_ix], return_inverse=True)
            uh = hash_keys_numpy(uniq)
            alloc = self._alloc_slots(uniq, uh)
            self._table.insert_batch(uniq, uh, alloc)
            slots[miss_ix] = alloc[inv]
        return slots

    def register_dense(self, n: int) -> None:
        """Pre-register keys [0, n) with slot == key — the device-
        chained generator contract (ops/window.py devgen_step_kernel):
        on device, slot must be a PURE FUNCTION of key, because probing
        a table there measured pathological (XLA gathers ~20ms/million
        on TPU) while identity is free. A legal allocation order — all
        mappings downstream go through the table and rev arrays — but
        it bypasses hash sharding, so it requires an EMPTY directory
        that owns every shard. Later out-of-domain keys still allocate
        normally from each shard's remaining slots."""
        if self.num_keys():
            raise ValueError("register_dense requires an empty directory")
        if (self.shard_lo, self.shard_hi) != (0, self.num_shards):
            raise ValueError("register_dense requires the full shard range")
        if n > self.local_slots:
            raise ValueError(
                f"dense key domain {n} exceeds capacity {self.local_slots}")
        keys = np.arange(n, dtype=np.int64)
        self._table.insert_batch(keys, hash_keys_numpy(keys), keys)
        self._rev_keys[:n] = keys
        self._rev_used[:n] = True
        # claim the dense region from each shard's free pointer so the
        # ordinary allocator never hands one of these slots out again
        self._next_free[:] = np.clip(
            n - np.arange(self.num_shards) * self.slots_per_shard,
            0, self.slots_per_shard)

    def register_misses(self, miss_keys: np.ndarray) -> None:
        """Register keys KNOWN to be absent (the fused C scan already
        probed them — codec.cc ingest_fused_scan): allocate + insert
        without repeating the lookup pass."""
        uniq = np.unique(np.asarray(miss_keys, np.int64))
        uh = hash_keys_numpy(uniq)
        alloc = self._alloc_slots(uniq, uh)
        self._table.insert_batch(uniq, uh, alloc)

    def _alloc_slots(self, keys: np.ndarray, hashes: np.ndarray) -> np.ndarray:
        """Assign shard-local slots to a batch of DISTINCT new keys:
        group by shard, hand out contiguous indices from each shard's
        free pointer, mark FULL past capacity. Pure numpy — no per-key
        Python."""
        shards = (hashes % self.num_shards).astype(np.int64)
        out = np.full(len(keys), -1, dtype=np.int64)
        inr = (shards >= self.shard_lo) & (shards < self.shard_hi)
        if not inr.any():
            return out
        sub = np.nonzero(inr)[0]
        order = np.argsort(shards[sub], kind="stable")
        sub = sub[order]
        sh = shards[sub]
        # rank of each key within its equal-shard run
        starts = np.r_[0, np.nonzero(np.diff(sh))[0] + 1]
        run_lens = np.diff(np.r_[starts, len(sh)])
        ranks = np.arange(len(sh)) - np.repeat(starts, run_lens)
        local_ix = self._next_free[sh] + ranks
        full = local_ix >= self.slots_per_shard
        slot = (sh - self.shard_lo) * self.slots_per_shard + local_ix
        slot[full] = self.FULL
        np.add.at(self._next_free, sh[~full], 1)
        ok = slot[~full]
        self._rev_keys[ok] = keys[sub[~full]]
        self._rev_used[ok] = True
        out[sub] = slot
        return out

    def key_of_slots(self, slots: np.ndarray) -> np.ndarray:
        return self._rev_keys[slots]

    def used_mask(self) -> np.ndarray:
        """(local_slots,) bool — which slots hold a registered key."""
        return self._rev_used

    def num_keys(self) -> int:
        return int(self._rev_used.sum())

    # -- snapshot (part of the checkpoint manifest) ----------------------
    def snapshot(self) -> Dict[str, np.ndarray]:
        return {
            "rev_keys": self._rev_keys.copy(),
            "rev_used": self._rev_used.copy(),
            "next_free": self._next_free.copy(),
        }

    @classmethod
    def restore(cls, num_shards: int, slots_per_shard: int,
                snap: Dict[str, np.ndarray],
                shard_range: Tuple[int, int] | None = None) -> "KeyDirectory":
        d = cls(num_shards, slots_per_shard, shard_range)
        d._rev_keys = snap["rev_keys"].copy()
        d._rev_used = snap["rev_used"].copy()
        d._next_free = snap["next_free"].copy()
        used = np.nonzero(d._rev_used)[0]
        keys = d._rev_keys[used]
        if len(used):
            d._table.insert_batch(keys, hash_keys_numpy(keys), used)
        return d


def account_full_drop(op, n: int) -> None:
    """Key-directory overflow policy (ref: the RocksDB role — the
    reference DEGRADES on state growth, it never drops, SURVEY §3.4).
    The default refuses to lose data: a full shard FAILS the job with
    the remediation options; ``state.allow-drops=true`` opts into
    dropping with accounting (the records_dropped_full gauge stays)."""
    if n <= 0:
        return
    if not getattr(op, "allow_drops", False):
        raise RuntimeError(
            f"key directory shard full: {n} record(s) have no state "
            "slot (state.num-key-shards x state.slots-per-shard "
            "exceeded, or keys routed outside this worker's shard "
            "range). The default policy never drops data - use "
            "state.backend='spill' for exact host-side degradation, "
            "raise the slot budget, or set state.allow-drops=true to "
            "drop with accounting (records_dropped_full).")
    op.records_dropped_full += n
