from flink_tpu.state.keyed import (
    PaneStateLayout,
    PaneState,
    KeyDirectory,
    init_state,
)

__all__ = ["PaneStateLayout", "PaneState", "KeyDirectory", "init_state"]
