from flink_tpu.state.keyed import (
    PaneStateLayout,
    PaneState,
    KeyDirectory,
    init_state,
)
from flink_tpu.state.lsm import LsmSpillStore
from flink_tpu.state.spill import HostSpillStore

__all__ = ["PaneStateLayout", "PaneState", "KeyDirectory", "init_state",
           "HostSpillStore", "LsmSpillStore"]
