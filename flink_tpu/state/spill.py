"""Host spill store: exact windowed aggregation for keys beyond HBM.

The dense pane-tensor backend (state/keyed.py) holds a FIXED number of
key slots per shard in HBM. The reference degrades gracefully past RAM
via RocksDB (ref: runtime/state/RocksDBKeyedStateBackend role, SURVEY
§3.4): state beyond memory gets slower, never wrong. This module is the
TPU-native analogue — but instead of swapping slots over the (slow,
~100ms-RTT remote-attached) host↔device link the way RocksDB pages
SSTs, it exploits that every lane aggregate is a commutative monoid
(sum/max/min/count): records whose keys cannot get an HBM slot are
aggregated ON THE HOST in vectorized numpy, per (key, pane), and the
host partials fire alongside the device partials. A key lives in
exactly one store (a key that failed slot allocation once can never be
resident later — the directory is insert-only), so the two stores'
key sets are disjoint and their fired rows simply concatenate: exact
results, no cross-store merge. Hot early keys keep HBM speed; overflow
keys degrade to host speed. (LRU slot eviction — promoting a late-hot
key into HBM — is a possible refinement; it would add per-eviction
link round trips, which measurement shows dominate at ~100ms each, so
v1 keeps placement static.)

Fire/refire/purge mirror the device path exactly: the operator passes
the SAME fired-ends list (including re-fires of late-within-lateness
data) to both stores, and purges both at the same lateness horizon.

Host-parallel plane (PROFILE.md §9.2/§9.3): given a ``HostPool`` the
store runs its independent units as pool tasks — per-pane merges in
``absorb`` (absorb already buckets by pane and ``_merge_pane`` touches
only that pane's table), per-window combines in ``fire`` (windows own
disjoint pane ranges), and above the ``host.fold-chunk-records`` batch
floor a chunked TREE fold: chunks group independently, pane partials
combine in chunk order (the windowAll scaling shape — one global key,
so key-sharding cannot apply). The pane→table dict's serial point is
guarded by one lock PER PANE ENTRY, not a global lock. Chunk size is
independent of the worker count, so the reduction tree — and the
output bytes for the exact lane monoids — never change with
``host.parallelism``; pool absent or parallelism 1 is the exact
serial path.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

_NEG_INF = np.float32(-np.inf)
_POS_INF = np.float32(np.inf)


def _cpu_device():
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return None


class HostSpillStore:
    """Per-(key, pane) lane accumulators in host numpy arrays.

    Layout: ``panes[p] = (keys sorted (K,), sums (K,S), maxs (K,M),
    mins (K,m), counts (K,))``. Batch absorption is one lexsort +
    segment reduce; merging into a pane is a sorted-union splice. Both
    are O(records + keys) vectorized numpy — no per-key Python loops
    (the round-2 session-registry mistake, not repeated here).
    """

    def __init__(self, agg, *, pool=None,
                 fold_chunk_records: Optional[int] = None):
        # NOTE: deliberately untyped — the state layer sits BELOW ops in
        # the layer map (tests/test_architecture.py) and only needs the
        # lane contract: sum/max/min_width, lift_masked, finalize.
        # ``pool`` is equally duck-typed (parallel.hostpool.HostPool):
        # .parallelism + .run_tasks(fns) — None or parallelism 1 keeps
        # the exact serial path.
        self.agg = agg
        self.panes: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray, np.ndarray]] = {}
        self.records_spilled = 0
        self._cpu = _cpu_device()
        self._pool = (pool if pool is not None
                      and pool.parallelism > 1 else None)
        if fold_chunk_records is None:
            # None = the declared config default — the floor is
            # single-sourced at HostOptions.FOLD_CHUNK_RECORDS so a
            # retune there reaches directly-constructed stores too
            from flink_tpu.config import HostOptions
            fold_chunk_records = HostOptions.FOLD_CHUNK_RECORDS.default
        self.fold_chunk_records = int(fold_chunk_records)
        # one lock PER PANE entry (§9.3), never a global lock. Within
        # one run_tasks batch every pane has at most one merge task
        # (absorb's spans are pane-contiguous; the tree fold combines
        # all of a pane's chunk partials inside a single task), and
        # the operator's absorb/fire entry points run sequentially on
        # the driver loop today — the locks are the pane tables'
        # read-modify-write guard for any caller that DOES overlap
        # absorb batches, so the store's safety never depends on that
        # entry discipline. Fire-side reads stay lock-free:
        # _merge_pane replaces a pane's tuple atomically.
        self._pane_locks: Dict[int, threading.Lock] = {}
        self._locks_guard = threading.Lock()

    def _pane_lock(self, pane: int) -> threading.Lock:
        with self._locks_guard:
            return self._pane_locks.setdefault(pane, threading.Lock())

    # -- ingest ----------------------------------------------------------

    def _lift(self, data: Dict[str, np.ndarray], n: int
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Evaluate the aggregate's lane lift for ``n`` host rows.
        ``lift_masked`` is written in jnp; pin it to the CPU backend so
        spilled records never ride the device link (that's the whole
        point). Falls back to the default device if no CPU backend
        exists — slower, still exact."""
        valid = np.ones(n, bool)
        if self._cpu is not None:
            with jax.default_device(self._cpu):
                s, mx, mn = self.agg.lift_masked(data, valid)
        else:
            s, mx, mn = self.agg.lift_masked(data, valid)
        return np.asarray(s), np.asarray(mx), np.asarray(mn)

    def absorb(self, keys: np.ndarray, panes: np.ndarray,
               data: Dict[str, np.ndarray]) -> None:
        """Fold overflow records into the per-(key, pane) accumulators."""
        n = len(keys)
        if n == 0:
            return
        self.records_spilled += n
        if self._pool is not None and n >= self.fold_chunk_records:
            self._absorb_tree(keys, panes, data)
            return
        groups = self._group_batch(keys, panes, data)
        self._splice_groups(*groups)

    def _group_batch(self, keys: np.ndarray, panes: np.ndarray,
                     data: Dict[str, np.ndarray]) -> Tuple[np.ndarray, ...]:
        """One vectorized (pane, key) grouping pass: lexsort + boundary
        flags + segment reduce. Returns pane-contiguous group arrays."""
        n = len(keys)
        sums, maxs, mins = self._lift(data, n)
        o = np.lexsort((keys, panes))
        pk, kk = panes[o], keys[o]
        new_grp = np.empty(n, bool)
        new_grp[0] = True
        new_grp[1:] = (pk[1:] != pk[:-1]) | (kk[1:] != kk[:-1])
        gid = np.cumsum(new_grp) - 1
        G = int(gid[-1]) + 1
        S, M, m = self.agg.sum_width, self.agg.max_width, self.agg.min_width
        g_sum = np.zeros((G, S), np.float32)
        np.add.at(g_sum, gid, sums[o])
        g_max = np.full((G, M), _NEG_INF, np.float32)
        np.maximum.at(g_max, gid, maxs[o])
        g_min = np.full((G, m), _POS_INF, np.float32)
        np.minimum.at(g_min, gid, mins[o])
        g_cnt = np.bincount(gid, minlength=G).astype(np.int64)
        return pk[new_grp], kk[new_grp], g_sum, g_max, g_min, g_cnt

    @staticmethod
    def _pane_spans(g_pane: np.ndarray) -> List[Tuple[int, int]]:
        bounds = np.flatnonzero(
            np.concatenate([[True], g_pane[1:] != g_pane[:-1], [True]]))
        return [(int(bounds[i]), int(bounds[i + 1]))
                for i in range(len(bounds) - 1)]

    def _splice_groups(self, g_pane, g_key, g_sum, g_max, g_min,
                       g_cnt) -> None:
        """Splice each touched pane (few per batch — event-time
        locality); independent per pane, so with a pool the merges run
        as parallel tasks under their pane locks (§9.3)."""
        spans = self._pane_spans(g_pane)

        def merge(a: int, b: int) -> None:
            pane = int(g_pane[a])
            with self._pane_lock(pane):
                self._merge_pane(pane, g_key[a:b], g_sum[a:b],
                                 g_max[a:b], g_min[a:b], g_cnt[a:b])

        if self._pool is not None and len(spans) > 1:
            self._pool.run_tasks(
                [lambda a=a, b=b: merge(a, b) for a, b in spans])
        else:
            for a, b in spans:
                merge(a, b)

    def _absorb_tree(self, keys: np.ndarray, panes: np.ndarray,
                     data: Dict[str, np.ndarray]) -> None:
        """Chunked tree fold (§9.2, the windowAll scaling shape): group
        fixed-size chunks on the pool, then combine each pane's chunk
        partials IN CHUNK ORDER. The chunk size is a config constant
        (never derived from the worker count), so the reduction tree is
        identical at every host.parallelism > 1."""
        n = len(keys)
        chunk = self.fold_chunk_records
        data = {k: np.asarray(v) for k, v in data.items()}
        spans = [(lo, min(lo + chunk, n)) for lo in range(0, n, chunk)]
        parts = self._pool.run_tasks(
            [lambda lo=lo, hi=hi: self._group_batch(
                keys[lo:hi], panes[lo:hi],
                {k: v[lo:hi] for k, v in data.items()})
             for lo, hi in spans])
        # pane → its chunk partials, insertion-ordered by chunk index
        per_pane: Dict[int, List[Tuple[np.ndarray, ...]]] = {}
        for g_pane, g_key, g_sum, g_max, g_min, g_cnt in parts:
            for a, b in self._pane_spans(g_pane):
                per_pane.setdefault(int(g_pane[a]), []).append(
                    (g_key[a:b], g_sum[a:b], g_max[a:b], g_min[a:b],
                     g_cnt[a:b]))

        def combine(pane: int, pieces) -> None:
            with self._pane_lock(pane):
                for piece in pieces:  # chunk order: deterministic tree
                    self._merge_pane(pane, *piece)

        self._pool.run_tasks(
            [lambda p=p, pcs=pcs: combine(p, pcs)
             for p, pcs in per_pane.items()])

    def _merge_pane(self, pane: int, keys, sums, maxs, mins, counts) -> None:
        cur = self.panes.get(pane)
        if cur is None:
            self.panes[pane] = (keys.copy(), sums.copy(), maxs.copy(),
                                mins.copy(), counts.copy())
            return
        ck, cs, cx, cn, cc = cur
        union = np.union1d(ck, keys)
        K = len(union)
        S, M, m = self.agg.sum_width, self.agg.max_width, self.agg.min_width
        us = np.zeros((K, S), np.float32)
        ux = np.full((K, M), _NEG_INF, np.float32)
        un = np.full((K, m), _POS_INF, np.float32)
        uc = np.zeros(K, np.int64)
        po = np.searchsorted(union, ck)
        pn = np.searchsorted(union, keys)
        us[po] = cs
        us[pn] += sums
        ux[po] = cx
        ux[pn] = np.maximum(ux[pn], maxs)
        un[po] = cn
        un[pn] = np.minimum(un[pn], mins)
        uc[po] = cc
        uc[pn] += counts
        self.panes[pane] = (union, us, ux, un, uc)

    # -- fire ------------------------------------------------------------

    def fire(self, ends: List[int], panes_per_window: int, pane_ms: int,
             offset_ms: int, size_ms: int) -> Optional[Dict[str, np.ndarray]]:
        """Fired rows for the given end panes, combined across each
        window's panes with the same monoid ops the device kernel uses.
        Returns None when no stored pane intersects any window (the
        common case — keep the hot path allocation-free)."""
        if not self.panes or not ends:
            return None
        ppw = panes_per_window
        lo_stored = min(self.panes)
        hi_stored = max(self.panes)
        live = [e for e in ends if e > lo_stored and e - ppw <= hi_stored]
        if not live:
            return None
        # windows own disjoint pane ranges' COMBINE work (reads only),
        # so per-window fires are independent pool tasks (§9.3);
        # results assemble in the fired-ends order either way
        if self._pool is not None and len(live) > 1:
            fired = self._pool.run_tasks(
                [lambda e=e: self._fire_window(e, ppw) for e in live])
        else:
            fired = [self._fire_window(e, ppw) for e in live]
        keys_out: List[np.ndarray] = []
        ends_out: List[np.ndarray] = []
        cnt_out: List[np.ndarray] = []
        res_cols: Dict[str, List[np.ndarray]] = {}
        for hit in fired:
            if hit is None:
                continue
            e, kk, wc_has, res = hit
            keys_out.append(kk)
            ends_out.append(np.full(len(kk), e, np.int64))
            cnt_out.append(wc_has)
            for f, v in res.items():
                if f == "count":
                    continue  # the exact element count wins (mirrors
                    # _decode_packs preferring the i32 count column)
                res_cols.setdefault(f, []).append(np.asarray(v))
        if not keys_out:
            return None
        end_pane = np.concatenate(ends_out)
        window_end = end_pane * pane_ms + offset_ms
        out: Dict[str, np.ndarray] = {
            "key": np.concatenate(keys_out),
            "window_start": window_end - size_ms,
            "window_end": window_end,
            "count": np.concatenate(cnt_out),
        }
        for f, cols in res_cols.items():
            out[f] = np.concatenate(cols)
        return out

    def _fire_window(self, e: int, ppw: int
                     ) -> Optional[Tuple[int, np.ndarray, np.ndarray, Dict]]:
        """Combine one window's panes with the same monoid ops the
        device kernel uses; returns (end_pane, keys, counts, finalize
        fields) or None when the window holds nothing."""
        S, M, m = self.agg.sum_width, self.agg.max_width, self.agg.min_width
        span = [self.panes[p] for p in range(e - ppw, e)
                if p in self.panes]
        if not span:
            return None
        union = span[0][0] if len(span) == 1 else np.unique(
            np.concatenate([s[0] for s in span]))
        K = len(union)
        ws = np.zeros((K, S), np.float32)
        wx = np.full((K, M), _NEG_INF, np.float32)
        wn = np.full((K, m), _POS_INF, np.float32)
        wc = np.zeros(K, np.int64)
        for ck, cs, cx, cn, cc in span:
            pos = np.searchsorted(union, ck)
            ws[pos] += cs
            wx[pos] = np.maximum(wx[pos], cx)
            wn[pos] = np.minimum(wn[pos], cn)
            wc[pos] += cc
        has = wc > 0
        if not has.any():
            return None
        if self._cpu is not None:
            with jax.default_device(self._cpu):
                res = self.agg.finalize(ws[has], wx[has], wn[has],
                                        wc[has].astype(np.int32))
        else:
            res = self.agg.finalize(ws[has], wx[has], wn[has],
                                    wc[has].astype(np.int32))
        return e, union[has], wc[has], res

    # -- lifecycle -------------------------------------------------------

    def purge_below(self, dead_pane: int) -> None:
        for p in [p for p in self.panes if p < dead_pane]:
            del self.panes[p]
        with self._locks_guard:  # locks track live panes, never grow
            for p in [p for p in self._pane_locks if p < dead_pane]:
                del self._pane_locks[p]

    def bytes_used(self) -> int:
        """Host memory held by spilled panes (memory.host_spill_bytes).
        Called from the metrics scrape thread while ingest mutates the
        dict — list() snapshots the values atomically under the GIL."""
        return sum(sum(a.nbytes for a in arrs)
                   for arrs in list(self.panes.values()))

    @property
    def key_count(self) -> int:
        if not self.panes:
            return 0
        ks = [t[0] for t in self.panes.values()]
        return len(np.unique(np.concatenate(ks)))

    def snapshot(self) -> Dict[str, Any]:
        return {
            "panes": {int(p): tuple(a.copy() for a in t)
                      for p, t in self.panes.items()},
            "records_spilled": self.records_spilled,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self.panes = {int(p): tuple(np.asarray(a) for a in t)
                      for p, t in snap["panes"].items()}
        self.records_spilled = int(snap["records_spilled"])
