"""Host spill store: exact windowed aggregation for keys beyond HBM.

The dense pane-tensor backend (state/keyed.py) holds a FIXED number of
key slots per shard in HBM. The reference degrades gracefully past RAM
via RocksDB (ref: runtime/state/RocksDBKeyedStateBackend role, SURVEY
§3.4): state beyond memory gets slower, never wrong. This module is the
TPU-native analogue — but instead of swapping slots over the (slow,
~100ms-RTT remote-attached) host↔device link the way RocksDB pages
SSTs, it exploits that every lane aggregate is a commutative monoid
(sum/max/min/count): records whose keys cannot get an HBM slot are
aggregated ON THE HOST in vectorized numpy, per (key, pane), and the
host partials fire alongside the device partials. A key lives in
exactly one store (a key that failed slot allocation once can never be
resident later — the directory is insert-only), so the two stores'
key sets are disjoint and their fired rows simply concatenate: exact
results, no cross-store merge. Hot early keys keep HBM speed; overflow
keys degrade to host speed. (LRU slot eviction — promoting a late-hot
key into HBM — is a possible refinement; it would add per-eviction
link round trips, which measurement shows dominate at ~100ms each, so
v1 keeps placement static.)

Fire/refire/purge mirror the device path exactly: the operator passes
the SAME fired-ends list (including re-fires of late-within-lateness
data) to both stores, and purges both at the same lateness horizon.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

_NEG_INF = np.float32(-np.inf)
_POS_INF = np.float32(np.inf)


def _cpu_device():
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return None


class HostSpillStore:
    """Per-(key, pane) lane accumulators in host numpy arrays.

    Layout: ``panes[p] = (keys sorted (K,), sums (K,S), maxs (K,M),
    mins (K,m), counts (K,))``. Batch absorption is one lexsort +
    segment reduce; merging into a pane is a sorted-union splice. Both
    are O(records + keys) vectorized numpy — no per-key Python loops
    (the round-2 session-registry mistake, not repeated here).
    """

    def __init__(self, agg):  # duck-typed LaneAggregate (ops.aggregates)
        # NOTE: deliberately untyped — the state layer sits BELOW ops in
        # the layer map (tests/test_architecture.py) and only needs the
        # lane contract: sum/max/min_width, lift_masked, finalize
        self.agg = agg
        self.panes: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray, np.ndarray]] = {}
        self.records_spilled = 0
        self._cpu = _cpu_device()

    # -- ingest ----------------------------------------------------------

    def _lift(self, data: Dict[str, np.ndarray], n: int
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Evaluate the aggregate's lane lift for ``n`` host rows.
        ``lift_masked`` is written in jnp; pin it to the CPU backend so
        spilled records never ride the device link (that's the whole
        point). Falls back to the default device if no CPU backend
        exists — slower, still exact."""
        valid = np.ones(n, bool)
        if self._cpu is not None:
            with jax.default_device(self._cpu):
                s, mx, mn = self.agg.lift_masked(data, valid)
        else:
            s, mx, mn = self.agg.lift_masked(data, valid)
        return np.asarray(s), np.asarray(mx), np.asarray(mn)

    def absorb(self, keys: np.ndarray, panes: np.ndarray,
               data: Dict[str, np.ndarray]) -> None:
        """Fold overflow records into the per-(key, pane) accumulators."""
        n = len(keys)
        if n == 0:
            return
        self.records_spilled += n
        sums, maxs, mins = self._lift(data, n)

        # group by (pane, key): lexsort + boundary flags + segment reduce
        o = np.lexsort((keys, panes))
        pk, kk = panes[o], keys[o]
        new_grp = np.empty(n, bool)
        new_grp[0] = True
        new_grp[1:] = (pk[1:] != pk[:-1]) | (kk[1:] != kk[:-1])
        gid = np.cumsum(new_grp) - 1
        G = int(gid[-1]) + 1
        S, M, m = self.agg.sum_width, self.agg.max_width, self.agg.min_width
        g_sum = np.zeros((G, S), np.float32)
        np.add.at(g_sum, gid, sums[o])
        g_max = np.full((G, M), _NEG_INF, np.float32)
        np.maximum.at(g_max, gid, maxs[o])
        g_min = np.full((G, m), _POS_INF, np.float32)
        np.minimum.at(g_min, gid, mins[o])
        g_cnt = np.bincount(gid, minlength=G).astype(np.int64)
        g_pane = pk[new_grp]
        g_key = kk[new_grp]

        # splice each touched pane (few per batch — event-time locality)
        bounds = np.flatnonzero(
            np.concatenate([[True], g_pane[1:] != g_pane[:-1], [True]]))
        for i in range(len(bounds) - 1):
            a, b = bounds[i], bounds[i + 1]
            self._merge_pane(int(g_pane[a]), g_key[a:b], g_sum[a:b],
                             g_max[a:b], g_min[a:b], g_cnt[a:b])

    def _merge_pane(self, pane: int, keys, sums, maxs, mins, counts) -> None:
        cur = self.panes.get(pane)
        if cur is None:
            self.panes[pane] = (keys.copy(), sums.copy(), maxs.copy(),
                                mins.copy(), counts.copy())
            return
        ck, cs, cx, cn, cc = cur
        union = np.union1d(ck, keys)
        K = len(union)
        S, M, m = self.agg.sum_width, self.agg.max_width, self.agg.min_width
        us = np.zeros((K, S), np.float32)
        ux = np.full((K, M), _NEG_INF, np.float32)
        un = np.full((K, m), _POS_INF, np.float32)
        uc = np.zeros(K, np.int64)
        po = np.searchsorted(union, ck)
        pn = np.searchsorted(union, keys)
        us[po] = cs
        us[pn] += sums
        ux[po] = cx
        ux[pn] = np.maximum(ux[pn], maxs)
        un[po] = cn
        un[pn] = np.minimum(un[pn], mins)
        uc[po] = cc
        uc[pn] += counts
        self.panes[pane] = (union, us, ux, un, uc)

    # -- fire ------------------------------------------------------------

    def fire(self, ends: List[int], panes_per_window: int, pane_ms: int,
             offset_ms: int, size_ms: int) -> Optional[Dict[str, np.ndarray]]:
        """Fired rows for the given end panes, combined across each
        window's panes with the same monoid ops the device kernel uses.
        Returns None when no stored pane intersects any window (the
        common case — keep the hot path allocation-free)."""
        if not self.panes or not ends:
            return None
        ppw = panes_per_window
        lo_stored = min(self.panes)
        hi_stored = max(self.panes)
        live = [e for e in ends if e > lo_stored and e - ppw <= hi_stored]
        if not live:
            return None
        S, M, m = self.agg.sum_width, self.agg.max_width, self.agg.min_width
        keys_out: List[np.ndarray] = []
        ends_out: List[np.ndarray] = []
        cnt_out: List[np.ndarray] = []
        res_cols: Dict[str, List[np.ndarray]] = {}
        for e in live:
            span = [self.panes[p] for p in range(e - ppw, e)
                    if p in self.panes]
            if not span:
                continue
            union = span[0][0] if len(span) == 1 else np.unique(
                np.concatenate([s[0] for s in span]))
            K = len(union)
            ws = np.zeros((K, S), np.float32)
            wx = np.full((K, M), _NEG_INF, np.float32)
            wn = np.full((K, m), _POS_INF, np.float32)
            wc = np.zeros(K, np.int64)
            for ck, cs, cx, cn, cc in span:
                pos = np.searchsorted(union, ck)
                ws[pos] += cs
                wx[pos] = np.maximum(wx[pos], cx)
                wn[pos] = np.minimum(wn[pos], cn)
                wc[pos] += cc
            has = wc > 0
            if not has.any():
                continue
            if self._cpu is not None:
                with jax.default_device(self._cpu):
                    res = self.agg.finalize(ws[has], wx[has], wn[has],
                                            wc[has].astype(np.int32))
            else:
                res = self.agg.finalize(ws[has], wx[has], wn[has],
                                        wc[has].astype(np.int32))
            kk = union[has]
            keys_out.append(kk)
            ends_out.append(np.full(len(kk), e, np.int64))
            cnt_out.append(wc[has])
            for f, v in res.items():
                if f == "count":
                    continue  # the exact element count wins (mirrors
                    # _decode_packs preferring the i32 count column)
                res_cols.setdefault(f, []).append(np.asarray(v))
        if not keys_out:
            return None
        end_pane = np.concatenate(ends_out)
        window_end = end_pane * pane_ms + offset_ms
        out: Dict[str, np.ndarray] = {
            "key": np.concatenate(keys_out),
            "window_start": window_end - size_ms,
            "window_end": window_end,
            "count": np.concatenate(cnt_out),
        }
        for f, cols in res_cols.items():
            out[f] = np.concatenate(cols)
        return out

    # -- lifecycle -------------------------------------------------------

    def purge_below(self, dead_pane: int) -> None:
        for p in [p for p in self.panes if p < dead_pane]:
            del self.panes[p]

    def bytes_used(self) -> int:
        """Host memory held by spilled panes (memory.host_spill_bytes).
        Called from the metrics scrape thread while ingest mutates the
        dict — list() snapshots the values atomically under the GIL."""
        return sum(sum(a.nbytes for a in arrs)
                   for arrs in list(self.panes.values()))

    @property
    def key_count(self) -> int:
        if not self.panes:
            return 0
        ks = [t[0] for t in self.panes.values()]
        return len(np.unique(np.concatenate(ks)))

    def snapshot(self) -> Dict[str, Any]:
        return {
            "panes": {int(p): tuple(a.copy() for a in t)
                      for p, t in self.panes.items()},
            "records_spilled": self.records_spilled,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self.panes = {int(p): tuple(np.asarray(a) for a in t)
                      for p, t in snap["panes"].items()}
        self.records_spilled = int(snap["records_spilled"])
