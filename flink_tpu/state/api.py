"""Keyed state API — descriptors and vectorized state views.

ref: flink-core/.../api/common/state/{ValueStateDescriptor,
ListStateDescriptor,MapStateDescriptor,StateTtlConfig}.java and the
runtime views in runtime/state/heap/* (per-key object cells probed per
record).

TPU-first redesign: a "state cell per key" becomes a COLUMN indexed by
the key directory's slot id. ValueState is a dense numpy column
(vectorized read/update across a whole microbatch); List/Map state are
object columns (host-side ragged data — the reference's heap state is
host-side too). The per-record `.value()/.update()` probe of the
reference becomes `state[slots]` / `state[slots] = v` over the batch's
slot vector — one C-speed gather/scatter instead of B hash lookups.

TTL follows OnCreateAndWrite visibility (ref: StateTtlConfig): every
write stamps the slot; reads through ``fresh_mask`` expire entries
older than the ttl against the operator's watermark clock.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class StateTtlConfig:
    """Time-to-live on event time (ref: StateTtlConfig — simplified to
    the OnCreateAndWrite / NeverReturnExpired corner, the common one)."""

    ttl_ms: int


@dataclasses.dataclass(frozen=True)
class ValueStateDescriptor:
    name: str
    default: float = 0.0
    dtype: Any = np.float64
    ttl: Optional[StateTtlConfig] = None


@dataclasses.dataclass(frozen=True)
class ListStateDescriptor:
    name: str
    ttl: Optional[StateTtlConfig] = None


@dataclasses.dataclass(frozen=True)
class MapStateDescriptor:
    name: str
    ttl: Optional[StateTtlConfig] = None


class _StateColumn:
    """Base: a slot-indexed column with a TTL stamp column."""

    def __init__(self, capacity: int, ttl: Optional[StateTtlConfig]):
        self.ttl = ttl
        self._stamp = (np.full(capacity, np.iinfo(np.int64).min, np.int64)
                       if ttl else None)

    def _grow_stamp(self, capacity: int) -> None:
        if self._stamp is not None and capacity > len(self._stamp):
            pad = np.full(capacity - len(self._stamp),
                          np.iinfo(np.int64).min, np.int64)
            self._stamp = np.concatenate([self._stamp, pad])

    def touch(self, slots: np.ndarray, now_ms: int) -> None:
        if self._stamp is not None:
            self._stamp[slots] = now_ms

    def fresh_mask(self, slots: np.ndarray, now_ms: int) -> np.ndarray:
        """True where the slot's entry is live under the TTL."""
        if self._stamp is None:
            return np.ones(len(slots), bool)
        return self._stamp[slots] > now_ms - self.ttl.ttl_ms


class ValueStateVector(_StateColumn):
    """Dense per-slot value column (ref: ValueState). Read with
    ``vs[slots]``, write with ``vs[slots] = values`` — whole-batch.
    TTL-configured state must read/write via ``get``/``update`` (which
    stamp the entry); plain indexing raises for it."""

    def __init__(self, desc: ValueStateDescriptor, capacity: int):
        super().__init__(capacity, desc.ttl)
        self.desc = desc
        self.col = np.full(capacity, desc.default, desc.dtype)

    def grow(self, capacity: int) -> None:
        if capacity > len(self.col):
            pad = np.full(capacity - len(self.col),
                          self.desc.default, self.desc.dtype)
            self.col = np.concatenate([self.col, pad])
            self._grow_stamp(capacity)

    def __getitem__(self, slots) -> np.ndarray:
        return self.col[slots]

    def __setitem__(self, slots, values) -> None:
        if self._stamp is not None:
            # a write that doesn't stamp would read back as expired —
            # TTL state must go through update(slots, values, now_ms)
            raise TypeError(
                f"state '{self.desc.name}' has a TTL: write with "
                ".update(slots, values, now_ms) so the entry is stamped")
        self.col[slots] = values

    def get(self, slots: np.ndarray, now_ms: int) -> np.ndarray:
        """TTL-aware read: expired slots yield the default."""
        v = self.col[slots]
        if self._stamp is not None:
            v = np.where(self.fresh_mask(slots, now_ms), v,
                         self.desc.default)
        return v

    def update(self, slots: np.ndarray, values, now_ms: int = 0) -> None:
        self.col[slots] = values
        self.touch(slots, now_ms)

    def clear(self, slots: np.ndarray) -> None:
        self.col[slots] = self.desc.default

    def snapshot(self) -> Dict[str, Any]:
        return {"col": self.col.copy(),
                "stamp": None if self._stamp is None else self._stamp.copy()}

    def restore(self, snap: Dict[str, Any]) -> None:
        self.col = np.array(snap["col"])
        if snap["stamp"] is not None:
            self._stamp = np.array(snap["stamp"])


class _ObjectStateColumn(_StateColumn):
    """Object column for ragged per-key state (lists/maps). Host-side —
    exactly where the reference's heap state lives too."""

    FACTORY = list

    def __init__(self, desc, capacity: int):
        super().__init__(capacity, desc.ttl)
        self.desc = desc
        self.col = np.empty(capacity, object)

    def grow(self, capacity: int) -> None:
        if capacity > len(self.col):
            new = np.empty(capacity, object)
            new[: len(self.col)] = self.col
            self.col = new
            self._grow_stamp(capacity)

    def cell(self, slot: int):
        if self.col[slot] is None:
            self.col[slot] = self.FACTORY()
        return self.col[slot]

    def clear(self, slots: np.ndarray) -> None:
        self.col[slots] = None

    def snapshot(self) -> Dict[str, Any]:
        import copy

        return {"col": copy.deepcopy(list(self.col)),
                "stamp": None if self._stamp is None else self._stamp.copy()}

    def restore(self, snap: Dict[str, Any]) -> None:
        self.col = np.empty(len(snap["col"]), object)
        self.col[:] = snap["col"]
        if snap["stamp"] is not None:
            self._stamp = np.array(snap["stamp"])


class ListStateVector(_ObjectStateColumn):
    """ref: ListState — per-key append list. ``append_batch`` adds one
    element per record, vectorized over the batch's slot vector."""

    FACTORY = list

    def append_batch(self, slots: np.ndarray, values: np.ndarray,
                     now_ms: int = 0) -> None:
        for s, v in zip(slots.tolist(), np.asarray(values).tolist()):
            self.cell(s).append(v)
        self.touch(slots, now_ms)

    def get(self, slot: int) -> list:
        return self.cell(int(slot))


class MapStateVector(_ObjectStateColumn):
    """ref: MapState — per-key dict."""

    FACTORY = dict

    def put_batch(self, slots: np.ndarray, keys, values,
                  now_ms: int = 0) -> None:
        for s, k, v in zip(slots.tolist(), list(keys), list(values)):
            self.cell(s)[k] = v
        self.touch(slots, now_ms)

    def get(self, slot: int) -> dict:
        return self.cell(int(slot))
