"""State processor — offline read/modify/write of checkpoints.

ref: flink-libraries/flink-state-processor-api (SavepointReader /
SavepointWriter: load a savepoint as datasets, transform operator
state, write a new savepoint a job can restore from).

TPU-first shape: operator state here is columnar already (pane tensors,
numpy directories, struct-of-arrays), so the "dataset view" is just the
snapshot dicts themselves — no serializer gymnastics. The processor
loads a checkpoint/savepoint through the same storage + FileSystem seam
the runtime uses, lets callers read or rewrite per-operator payloads,
and writes a NEW v2 checkpoint directory that `execution.checkpointing
.restore` (or restore-from-path) accepts. A convenience view decodes a
WindowOperator snapshot into (key, pane, lanes) rows — the keyed-state
reader analogue.
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.checkpoint.storage import FsCheckpointStorage


class SavepointReader:
    """Read-side (ref: SavepointReader.read)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.payload = FsCheckpointStorage.load(path)

    @property
    def checkpoint_id(self) -> int:
        return int(self.payload.get("checkpoint_id", 0))

    def operator_ids(self) -> List[Any]:
        return sorted(self.payload.get("operators", {}))

    def operator_state(self, nid: Any) -> Dict[str, Any]:
        return self.payload["operators"][nid]

    def source_positions(self) -> Dict[Any, Dict[Any, int]]:
        return self.payload.get("sources", {})

    def window_keyed_rows(self, nid: Any) -> Dict[str, np.ndarray]:
        """Decode a WindowOperator snapshot into columnar keyed rows:
        one row per (key, live pane) with the raw lane values — the
        keyed-state dataset view (ref: SavepointReader.readKeyedState).
        """
        snap = self.operator_state(nid)
        if "panes" not in snap or "directory" not in snap:
            raise ValueError(
                f"operator {nid!r} is not a window-operator snapshot")
        panes = snap["panes"]
        counts = np.asarray(panes.counts)
        rows_total = counts.shape[0]
        ring = snap["ring"]
        n_dev = snap.get("n_dev", 1)
        rev_used = np.asarray(snap["directory"]["rev_used"])
        rev_keys = np.asarray(snap["directory"]["rev_keys"])
        # state rows: per device block, slots_local rows + 1 dump row
        spd = (rows_total // n_dev) - 1
        out_keys, out_panes = [], []
        out = {"sums": [], "maxs": [], "mins": [], "counts": []}
        for d in range(n_dev):
            block = slice(d * (spd + 1), d * (spd + 1) + spd)  # skip dump
            c = counts[block]
            slot_ix, ring_ix = np.nonzero(c > 0)
            gslot = d * spd + slot_ix
            used = rev_used[gslot]
            gslot, ring_ix = gslot[used], ring_ix[used]
            out_keys.append(rev_keys[gslot])
            out_panes.append(ring_ix)
            for name in ("sums", "maxs", "mins"):
                lane = getattr(panes, name)
                if lane is None:  # zero-width lane family (see PaneState)
                    out[name].append(
                        np.zeros((int(used.sum()), 0), np.float32))
                else:
                    arr = np.asarray(lane)[block]
                    out[name].append(arr[slot_ix[used], ring_ix])
            out["counts"].append(c[slot_ix[used], ring_ix])
        return {
            "key": np.concatenate(out_keys) if out_keys else np.zeros(0, np.int64),
            "ring_pane": np.concatenate(out_panes) if out_panes else np.zeros(0, np.int64),
            "sums": np.concatenate(out["sums"]) if out["sums"] else np.zeros((0, 0)),
            "maxs": np.concatenate(out["maxs"]) if out["maxs"] else np.zeros((0, 0)),
            "mins": np.concatenate(out["mins"]) if out["mins"] else np.zeros((0, 0)),
            "count": np.concatenate(out["counts"]) if out["counts"] else np.zeros(0),
        }


class SavepointWriter:
    """Write-side (ref: SavepointWriter.fromExistingSavepoint /
    withOperator → write). Starts from an existing checkpoint payload,
    applies per-operator transforms, writes a NEW savepoint directory
    restorable by the runtime."""

    def __init__(self, reader: SavepointReader) -> None:
        self._payload = dict(reader.payload)
        self._payload["operators"] = dict(reader.payload["operators"])

    def transform_operator(
            self, nid: Any,
            fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "SavepointWriter":
        self._payload["operators"][nid] = fn(
            self._payload["operators"][nid])
        return self

    def remove_operator(self, nid: Any) -> "SavepointWriter":
        self._payload["operators"].pop(nid)
        return self

    def set_source_positions(
            self, positions: Dict[Any, Dict[Any, int]]) -> "SavepointWriter":
        self._payload["sources"] = positions
        return self

    def reset_watermarks(self, include_operators: bool = True
                         ) -> "SavepointWriter":
        """Reset event time for a rewound/bootstrapped savepoint: drops
        the driver-level clocks (watermark generators, max timestamps,
        per-node watermarks) AND, by default, rewinds each operator
        snapshot's own clock fields (watermark, fired/cleared horizons)
        — without the operator half, replayed records sit behind the
        old end-of-stream watermark and drop as late, or land in
        windows marked already-fired. Already-retained aggregates stay:
        replay merges ON TOP of them and re-fires the affected windows
        (the bootstrap-then-reprocess flow)."""
        from flink_tpu.time.watermarks import LONG_MIN

        for k in ("wm_gens", "max_ts", "out_wm"):
            self._payload.pop(k, None)
        if include_operators:
            for snap in self._payload["operators"].values():
                if not isinstance(snap, dict):
                    continue
                if "watermark" in snap:
                    snap["watermark"] = LONG_MIN
                if "fired_below_end" in snap:
                    snap["fired_below_end"] = None
                if "refire" in snap:
                    snap["refire"] = []
                if "cleared_below" in snap:
                    # WindowPlan.first_dead_pane(LONG_MIN): nothing dead
                    snap["cleared_below"] = np.iinfo(np.int64).min // 2
                if "columns" in snap and "fired" in snap.get("columns", {}):
                    cols = snap["columns"]  # session spans re-emit
                    cols["fired"] = np.zeros_like(cols["fired"])
                    cols["refire"] = np.zeros_like(cols["refire"])
        return self

    def write(self, root: str, job_id: str,
              checkpoint_id: Optional[int] = None) -> str:
        """Write as ``<root>/<job_id>/savepoint-<id>``; returns the
        path. Loader-compat fields (op_files/op_file_versions) are
        stripped — they describe the OLD directory. Staged 2PC sink
        epochs are stripped too: a bootstrapped savepoint is not a
        crash-recovery point, and carrying the source checkpoint's
        staged epoch into a rewound replay would re-commit rows the
        replay is about to produce again (duplicates)."""
        payload = dict(self._payload)
        payload.pop("op_files", None)
        payload.pop("op_file_versions", None)
        payload.pop("sinks", None)
        cid = (checkpoint_id if checkpoint_id is not None
               else int(payload.get("checkpoint_id", 0)) + 1)
        payload["checkpoint_id"] = cid
        ops = payload.pop("operators")
        st = FsCheckpointStorage(root, job_id)
        from flink_tpu.checkpoint import blobformat
        blobs = {str(nid): blobformat.encode(snap)
                 for nid, snap in ops.items()}
        h = st.save_v2(cid, payload, blobs, {}, savepoint=True)
        return h.path


def load_savepoint(path: str) -> SavepointReader:
    return SavepointReader(path)
