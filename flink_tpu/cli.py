"""Command-line frontend — the CliFrontend analogue.

ref: flink-clients/.../client/cli/CliFrontend.java (run / list /
cancel / savepoint actions against a cluster) and the `flink` shell
script. Here::

    python -m flink_tpu run --coordinator H:P --entry pkg.mod:build \
        [--job-id id] [--conf key=value ...]
    python -m flink_tpu run --local --entry pkg.mod:build [...]
    python -m flink_tpu run --session H:P [--ha-dir D] --entry mod:build
    python -m flink_tpu session start [--port P] [--local-runners N] \
        [--ha-dir D] [--standby] [--conf key=value ...]
    python -m flink_tpu session submit --session H:P --entry mod:build
    python -m flink_tpu session list|info|cancel|stop \
        (--session H:P | --ha-dir D) [...]
    python -m flink_tpu analyze [job.conf] [--entry pkg.mod:build] \
        [--json] [--explain] [--fail-on error|warn|off]
    python -m flink_tpu lint [paths ...] [--json] [--plane <name>]
    python -m flink_tpu log TOPIC_DIR [--compact] [--retain] \
        [--conf key=value ...]
    python -m flink_tpu fsck PATH [--repair] [--json]
    python -m flink_tpu list --coordinator H:P
    python -m flink_tpu status --coordinator H:P JOB_ID
    python -m flink_tpu cancel --coordinator H:P JOB_ID
    python -m flink_tpu savepoint --coordinator H:P JOB_ID
    python -m flink_tpu runners --coordinator H:P

The entry point contract is the job-jar analogue: ``module:function``
importable on the RUNNER host, taking a StreamExecutionEnvironment and
building the pipeline on it (see runtime/runner.py).
"""
from __future__ import annotations

import argparse
import json
import sys
import uuid
from typing import List, Optional


def _coord_client(spec: str, flag: str = "--coordinator"):
    from flink_tpu.runtime.rpc import RpcClient

    host, _, port = spec.partition(":")
    if not port:
        raise SystemExit(f"{flag} must be HOST:PORT, got {spec!r}")
    return RpcClient(host or "127.0.0.1", int(port))


# leader re-resolution budget of the HA-aware session client: with
# --ha-dir, a connection-refused (the leader died / a standby is mid-
# takeover) re-reads the lease and retries up to this many times
# before surfacing the failure (exit 1, never a traceback). Module
# constants so tests can shrink the budget.
_HA_RETRIES = 24
_HA_RETRY_DELAY_S = 0.25


class _SessionClient:
    """Session-cluster RPC client that survives dispatcher failover.

    Address resolution: an explicit ``--session HOST:PORT`` wins for
    the FIRST attempt; with ``--ha-dir`` every retry re-resolves the
    current leader from the lease file (``runtime/ha.leader_address``),
    so a submit/list/poll issued against a dead leader lands on the
    standby that took over. Without ``--ha-dir`` transport errors
    surface immediately (the pre-HA behavior)."""

    def __init__(self, session: Optional[str], ha_dir: Optional[str],
                 flag: str = "--session") -> None:
        if not session and not ha_dir:
            # usage error, same class as a missing required flag —
            # the documented exit-2 leg of the session CLI contract
            print(f"error: {flag} HOST:PORT or --ha-dir is required",
                  file=sys.stderr)
            raise SystemExit(2)
        self._session = session
        self._ha_dir = ha_dir
        self._flag = flag
        self._client = None
        self._addr: Optional[str] = session

    def _resolve(self) -> Optional[str]:
        if self._addr:
            return self._addr
        from flink_tpu.runtime.ha import leader_address

        self._addr = leader_address(self._ha_dir)
        return self._addr

    def call(self, method: str, **kw):
        import time as _time

        from flink_tpu.runtime.rpc import RpcError

        last: Optional[Exception] = None
        attempts = (_HA_RETRIES + 1) if self._ha_dir else 1
        for i in range(attempts):
            if i:
                _time.sleep(_HA_RETRY_DELAY_S)
            addr = self._resolve()
            if addr is None:
                last = RpcError(
                    f"no session leader lease in --ha-dir "
                    f"{self._ha_dir!r}")
                continue
            if self._client is None:
                self._client = _coord_client(addr, flag=self._flag)
            try:
                return self._client.call(method, **kw)
            except RpcError as e:
                last = e
                self.close()
                if self._ha_dir:
                    # drop the cached address: the next attempt
                    # re-reads the lease (a takeover moves it)
                    self._addr = None
        raise last  # type: ignore[misc]

    def close(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            finally:
                self._client = None


def _parse_conf(pairs: List[str]) -> dict:
    conf = {}
    for p in pairs:
        k, sep, v = p.partition("=")
        if not sep:
            raise SystemExit(f"--conf expects key=value, got {p!r}")
        # config values are typed by the option registry at load time;
        # pass numbers through as numbers for convenience
        try:
            conf[k] = int(v)
        except ValueError:
            try:
                conf[k] = float(v)
            except ValueError:
                conf[k] = v
    return conf


def _run_local(entry: str, conf: dict, job_id: str) -> int:
    import importlib

    from flink_tpu import faults
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration

    mod_name, _, fn_name = entry.partition(":")
    build = getattr(importlib.import_module(mod_name), fn_name)
    config = Configuration(conf)
    # the faults.* grammar is live on the local path too — a chaos conf
    # passed to `run --local` must inject, not silently no-op
    faults.install_from_config(config)
    if "restart-strategy.type" in conf:
        # an EXPLICIT restart strategy runs under the supervisor:
        # failures restore from the latest checkpoint and replay (the
        # chained-jobs chaos drive). Without one, a local job stays
        # fail-fast — wrapping unconditionally would silently
        # restart-with-restore on any failure (the config default is
        # exponential-delay), changing plain `run --local` semantics.
        from flink_tpu.runtime.supervisor import run_with_recovery

        def build_env(attempt_conf):
            env = StreamExecutionEnvironment(attempt_conf)
            build(env)
            return env

        result = run_with_recovery(build_env, config, job_name=job_id)
    else:
        env = StreamExecutionEnvironment(config)
        build(env)
        result = env.execute(job_id)
    print(json.dumps({"job_id": job_id, "state": "FINISHED",
                      "records_in": result.metrics.get("records_in"),
                      "records_out": result.metrics.get("records_out")}))
    return 0


def _run_attached(session: Optional[str], entry: str, conf: dict,
                  job_id: str, ha_dir: Optional[str] = None) -> int:
    """``run --session H:P``: attach the job to a RUNNING session
    cluster instead of spinning a private runtime — submit through the
    dispatcher's admission gate, then block until the job is terminal
    (the `flink run` against a session cluster flow). With --ha-dir
    the attach survives a dispatcher failover: submit and every status
    poll re-resolve the leader through the lease."""
    import time as _time

    from flink_tpu.runtime.rpc import RpcError

    c = _SessionClient(session, ha_dir)
    try:
        resp = c.call("submit_session_job", job_id=job_id, entry=entry,
                      config=conf)
        if not resp.get("admitted"):
            print(json.dumps({"job_id": job_id, **resp}))
            return 1
        while True:
            st = c.call("job_status", job_id=job_id)
            state = st.get("state")
            if state in ("FINISHED", "FAILED", "CANCELED", "UNKNOWN"):
                print(json.dumps({"job_id": job_id, **st}))
                return 0 if state == "FINISHED" else 1
            _time.sleep(0.3)
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        c.close()


def _session(args) -> int:
    """``flink_tpu session ...``: the session-cluster control surface
    (runtime/session.py SessionDispatcher). Exit-code contract
    (asserted in tests/test_session.py and tests/test_cli.py
    TestSessionHaCli, same shape as TestExitCodeContract): 0 = ok
    (started / admitted / listed / stopped), 1 = the cluster refused
    (admission rejection, unknown job, no reachable leader), 2 = usage
    error (argparse / --standby without an HA dir)."""
    from flink_tpu.runtime.rpc import RpcError

    if args.session_cmd == "start":
        from flink_tpu.config import Configuration
        from flink_tpu.runtime.session import serve_session

        conf = _parse_conf(args.conf)
        if args.ha_dir:
            conf["high-availability.dir"] = args.ha_dir
        return serve_session(Configuration(conf),
                             port=args.port,
                             local_runners=args.local_runners,
                             standby=args.standby)
    c = _SessionClient(args.session, args.ha_dir)
    try:
        if args.session_cmd == "submit":
            job_id = args.job_id or f"job-{uuid.uuid4().hex[:8]}"
            resp = c.call("submit_session_job", job_id=job_id,
                          entry=args.entry,
                          config=_parse_conf(args.conf))
            print(json.dumps({"job_id": job_id, **resp}))
            return 0 if resp.get("admitted") else 1
        if args.session_cmd == "list":
            print(json.dumps(c.call("session_jobs")))
            return 0
        if args.session_cmd == "info":
            print(json.dumps(c.call("session_info")))
            return 0
        if args.session_cmd == "cancel":
            resp = c.call("cancel_job", job_id=args.job_id)
            print(json.dumps(resp))
            return 0 if resp.get("ok") else 1
        if args.session_cmd == "rescale":
            resp = c.call("rescale_job", job_id=args.job_id,
                          devices=args.devices,
                          processes=args.processes)
            print(json.dumps(resp))
            return 0 if resp.get("ok") else 1
        # stop
        resp = c.call("stop_session")
        print(json.dumps(resp))
        return 0 if resp.get("ok") else 1
    except RpcError as e:
        # no reachable leader (after the --ha-dir retry budget): the
        # cluster refused — a clean 1, never a traceback, so scripts
        # can distinguish it from a usage error
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        c.close()


def _print_findings(findings, as_json: bool) -> None:
    from flink_tpu.analysis import render_findings

    if as_json:
        for f in findings:
            print(json.dumps(f.to_dict()))
    else:
        print(render_findings(findings))


def _analyze(args) -> int:
    """`flink_tpu analyze`: the same rules the driver runs at submit,
    standalone — a misconfigured job fails here in milliseconds instead
    of minutes into a run.

    Exit-code contract (the CI surface, mirrored by `lint` and
    asserted in tests/test_cli.py): 0 = clean at the threshold,
    1 = blocking findings, 2 = usage/path error (unreadable conf file,
    unimportable --entry, --explain without a plan)."""
    import importlib

    from flink_tpu.analysis import analyze, analyze_config
    from flink_tpu.analysis.core import blocking
    from flink_tpu.config import AnalysisOptions, Configuration

    if args.explain and not args.entry:
        print("error: --explain needs --entry (per-node facts are "
              "properties of a compiled plan)", file=sys.stderr)
        return 2
    config = Configuration(_parse_conf(args.conf))
    if args.job_conf:
        try:
            config = Configuration.from_file(
                args.job_conf).merged_with(config)
        except (OSError, ValueError) as e:
            print(f"error: cannot load job conf {args.job_conf!r}: {e}",
                  file=sys.stderr)
            return 2
    plan = None
    if args.entry:
        from flink_tpu.api.environment import StreamExecutionEnvironment

        mod_name, _, fn_name = args.entry.partition(":")
        try:
            build = getattr(importlib.import_module(mod_name), fn_name)
        except (ImportError, AttributeError) as e:
            print(f"error: cannot import entry {args.entry!r}: {e}",
                  file=sys.stderr)
            return 2
        env = StreamExecutionEnvironment(config)
        build(env)
        # non-strict lowering: plans strict compilation rejects still
        # analyze, so the violation reports as a finding with a fix
        # hint instead of a bare compiler stack trace
        plan = env.compile_plan(strict=False)
        config = env.config
        findings = analyze(plan, config)
    else:
        findings = analyze_config(config)
    _print_findings(findings, as_json=args.json)
    if args.explain:
        from flink_tpu.analysis.dataflow import explain_plan

        print(explain_plan(plan, config))
    fail_on = args.fail_on or str(
        config.get(AnalysisOptions.FAIL_ON)).strip().lower()
    return 1 if blocking(findings, fail_on) else 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="flink_tpu",
                                description="flink_tpu client")
    sub = p.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="submit a job")
    runp.add_argument("--entry", required=True, metavar="MODULE:FUNCTION")
    runp.add_argument("--coordinator", metavar="HOST:PORT")
    runp.add_argument("--local", action="store_true",
                      help="execute in this process (LocalExecutor)")
    runp.add_argument("--session", metavar="HOST:PORT",
                      help="attach the job to a RUNNING session "
                           "cluster (`session start`) instead of "
                           "spinning a private runtime; blocks until "
                           "the job is terminal (exit 0 = FINISHED)")
    runp.add_argument("--ha-dir", default=None, metavar="DIR",
                      help="with --session (or alone): resolve the "
                           "session leader through the HA lease in "
                           "DIR; the submit and every status poll "
                           "re-resolve on connection failure, so the "
                           "attach survives a dispatcher failover")
    runp.add_argument("--job-id", default=None)
    runp.add_argument("--runtime-mode", choices=("streaming", "batch"),
                      default=None,
                      help="execution.runtime-mode: 'batch' runs a "
                           "fully bounded job in topological stage "
                           "waves over blocking columnar exchanges "
                           "(shorthand for --conf "
                           "execution.runtime-mode=...)")
    runp.add_argument("--conf", action="append", default=[],
                      metavar="KEY=VALUE")
    runp.add_argument("--py-file", action="append", default=[],
                      metavar="PATH",
                      help="ship this Python file to the runner via the "
                           "coordinator's blob store (the job-jar "
                           "analogue); repeatable")

    az = sub.add_parser(
        "analyze",
        help="compile-time plan analysis: run every analyzer rule over "
             "a job conf (and, with --entry, its compiled pipeline) "
             "WITHOUT executing; findings print before the first "
             "record would flow",
        epilog="exit codes: 0 = clean at the threshold, 1 = blocking "
               "findings, 2 = usage/path error. --json prints one "
               "Finding.to_dict object per line (keys: rule, severity, "
               "message, fix, node, node_name, file, line — the stable "
               "CI shape shared with `lint --json`; RULES.md documents "
               "it).")
    az.add_argument("job_conf", nargs="?", metavar="JOB_CONF",
                    help="`key: value` / JSON config file "
                         "(Configuration.from_file grammar); omit to "
                         "analyze --conf pairs alone")
    az.add_argument("--entry", metavar="MODULE:FUNCTION",
                    help="build the pipeline too, enabling the plan "
                         "rules (without it only config rules run)")
    az.add_argument("--conf", action="append", default=[],
                    metavar="KEY=VALUE")
    az.add_argument("--json", action="store_true",
                    help="one JSON object per finding (machine surface)")
    az.add_argument("--explain", action="store_true",
                    help="after the findings, print each plan node's "
                         "inferred dataflow facts — record schema, "
                         "watermark axis, state bound + bytes-per-key "
                         "estimate (needs --entry; analysis/dataflow"
                         ".py)")
    az.add_argument("--fail-on", choices=("error", "warn", "off"),
                    default=None,
                    help="exit nonzero at this severity (default: the "
                         "job's analysis.fail-on, itself defaulting to "
                         "'error')")

    lint = sub.add_parser(
        "lint",
        help="repo AST lints over the project call graph: tracer taint "
             "in jit kernels and their helpers, fault-point / "
             "config-key / metric-name drift, unlocked shared writes "
             "in host-pool task closures, durability-seam bypasses, "
             "lock-order cycles, unverified fenced publications "
             "(pure-stdlib ast pass; zero findings on the shipped "
             "tree is a tier-1 gate)",
        epilog="exit codes: 0 = clean, 1 = findings, 2 = usage/path "
               "error (including an unknown --plane). --json prints "
               "one Finding.to_dict object per line (same shape as "
               "`analyze --json`).")
    lint.add_argument("paths", nargs="*", metavar="PATH",
                      help="files or directories (default: the shipped "
                           "flink_tpu tree + tools + bench scripts)")
    lint.add_argument("--json", action="store_true",
                      help="one JSON object per finding")
    lint.add_argument("--plane", default=None, metavar="NAME",
                      help="only report findings of one lint plane "
                           "(tracer, registry, config, metrics, "
                           "concurrency, durability, locking, "
                           "fencing); unknown names exit 2")

    sess = sub.add_parser(
        "session",
        help="session-cluster mode (runtime/session.py): one "
             "long-lived dispatcher hosting N concurrent jobs on a "
             "shared runner fleet — slot quotas, FIFO submission "
             "queue, fair drain scheduling, queue-depth autoscaling",
        epilog="exit codes: 0 = ok, 1 = the cluster refused "
               "(admission rejection / unknown job), 2 = usage error.")
    ssub = sess.add_subparsers(dest="session_cmd", required=True)
    st = ssub.add_parser(
        "start", help="serve a session dispatcher until `session "
                      "stop` (prints one JSON line with the address, "
                      "then blocks)")
    st.add_argument("--port", type=int, default=0,
                    help="dispatcher RPC port (0 = ephemeral, read it "
                         "from the printed JSON line)")
    st.add_argument("--local-runners", type=int, default=0,
                    metavar="N",
                    help="also start N in-process runners registered "
                         "to this dispatcher (a self-contained local "
                         "cluster; 0 = external runners register "
                         "themselves via python -m "
                         "flink_tpu.runtime.runner)")
    st.add_argument("--ha-dir", default=None, metavar="DIR",
                    help="shared HA directory (shorthand for --conf "
                         "high-availability.dir=DIR): contend for the "
                         "leadership lease and serve only while "
                         "holding it; the durable session registry "
                         "lives here too, so a standby takeover "
                         "recovers every admitted job")
    st.add_argument("--standby", action="store_true",
                    help="hot-standby contender: block on the "
                         "leadership lease in --ha-dir and take over "
                         "(re-hydrating the session registry) when "
                         "the incumbent's lease lapses")
    st.add_argument("--conf", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="session.* quotas / autoscale knobs and any "
                         "other cluster config")
    _HA_HELP = ("resolve the session leader through the HA lease in "
                "DIR instead of (or as failover for) a fixed "
                "--session address; connection failures re-resolve "
                "and retry with a bounded budget")
    sb = ssub.add_parser(
        "submit", help="submit a job to a running session cluster "
                       "(exit 0 = admitted or queued, 1 = rejected)")
    sb.add_argument("--session", metavar="HOST:PORT")
    sb.add_argument("--ha-dir", default=None, metavar="DIR",
                    help=_HA_HELP)
    sb.add_argument("--entry", required=True, metavar="MODULE:FUNCTION")
    sb.add_argument("--job-id", default=None)
    sb.add_argument("--conf", action="append", default=[],
                    metavar="KEY=VALUE")
    sl = ssub.add_parser(
        "list", help="per-job registry: state, slots, queue position, "
                     "attempts, heartbeat-carried metrics, leader "
                     "epoch + takeover count")
    sl.add_argument("--session", metavar="HOST:PORT")
    sl.add_argument("--ha-dir", default=None, metavar="DIR",
                    help=_HA_HELP)
    si = ssub.add_parser(
        "info", help="cluster view: runners with slot occupancy, "
                     "quotas, leader epoch, takeover count, jobs "
                     "recovered by the current leader")
    si.add_argument("--session", metavar="HOST:PORT")
    si.add_argument("--ha-dir", default=None, metavar="DIR",
                    help=_HA_HELP)
    sc = ssub.add_parser("cancel", help="cancel one session job")
    sc.add_argument("--session", metavar="HOST:PORT")
    sc.add_argument("--ha-dir", default=None, metavar="DIR",
                    help=_HA_HELP)
    sc.add_argument("job_id")
    sr = ssub.add_parser(
        "rescale", help="live-rescale one session job: savepoint + "
                        "restart at a new device width / process count "
                        "(exit 0 = dispatched, 1 = refused)")
    sr.add_argument("--session", metavar="HOST:PORT")
    sr.add_argument("--ha-dir", default=None, metavar="DIR",
                    help=_HA_HELP)
    sr.add_argument("--devices", type=int, required=True,
                    help="per-process mesh width after the rescale")
    sr.add_argument("--processes", type=int, default=None, metavar="M",
                    help="host-process count after the rescale "
                         "(default: keep the current count)")
    sr.add_argument("job_id")
    sp_ = ssub.add_parser(
        "stop", help="shut the cluster down (cancels every "
                     "non-terminal job, then the dispatcher exits)")
    sp_.add_argument("--session", metavar="HOST:PORT")
    sp_.add_argument("--ha-dir", default=None, metavar="DIR",
                     help=_HA_HELP)

    fsck = sub.add_parser(
        "fsck",
        help="offline storage integrity check: walk a log topic or a "
             "checkpoint directory verifying segment CRCs/footers, "
             "marker/manifest/lease coherence, and orphan debris "
             "(flink_tpu/fsck.py)",
        epilog="exit codes: 0 = clean, 1 = findings remain, 2 = "
               "usage/path error (not a recognizable topic or "
               "checkpoint dir). --json prints one finding object per "
               "line (rule, severity, path, message, repairable, "
               "repaired).")
    fsck.add_argument("path", metavar="PATH",
                      help="topic dir (meta.json) or checkpoint dir "
                           "(chk-*/savepoint-* children; a single "
                           "checkpoint or a whole storage root also "
                           "work) — autodetected")
    fsck.add_argument("--repair", action="store_true",
                      help="apply the already-safe sweeps only "
                           "(delete .tmp debris, unreferenced "
                           "segments, orphaned in-progress checkpoint "
                           "dirs); never touches markers, leases, or "
                           "referenced files")
    fsck.add_argument("--json", action="store_true",
                      help="one JSON object per finding")

    logp = sub.add_parser(
        "log",
        help="inspect a durable log topic (committed offsets, staged "
             "transactions, segments, compaction generation, "
             "retention floor, active writer leases with epochs, "
             "per-consumer-group committed offsets + membership "
             "generations, background-cleaner lease/status) — "
             "optionally run a maintenance pass first",
        epilog="exit codes: 0 = ok, 1 = topic/maintenance error "
               "(corrupt state, compaction failure, or a live "
               "background cleaner owns the topic and --compact/"
               "--retain must not race it), 2 = usage/path error "
               "(no such topic).")
    logp.add_argument("topic", metavar="TOPIC_DIR",
                      help="topic directory (<log.dir>/<name>)")
    logp.add_argument("--compact", action="store_true",
                      help="run one key-compaction pass before "
                           "describing (log.compaction.* grammar via "
                           "--conf; key defaults to the topic's "
                           "recorded key_field)")
    logp.add_argument("--retain", action="store_true",
                      help="run one retention pass before describing "
                           "(log.retention.ms / .bytes / .ts-field "
                           "via --conf)")
    logp.add_argument("--conf", action="append", default=[],
                      metavar="KEY=VALUE",
                      help="log.compaction.* / log.retention.* "
                           "maintenance knobs")

    for name, help_ in (("list", "list jobs"), ("runners", "list runners")):
        sp = sub.add_parser(name, help=help_)
        sp.add_argument("--coordinator", required=True, metavar="HOST:PORT")

    for name, help_ in (("status", "job status"), ("cancel", "cancel job"),
                        ("savepoint", "trigger a savepoint")):
        sp = sub.add_parser(name, help=help_)
        sp.add_argument("--coordinator", required=True, metavar="HOST:PORT")
        sp.add_argument("job_id")

    rs = sub.add_parser("rescale",
                        help="savepoint + restart the job at a new "
                             "device width (and optionally a new "
                             "process count — the restore repartitions "
                             "every keyed op's key-group ranges)")
    rs.add_argument("--coordinator", required=True, metavar="HOST:PORT")
    rs.add_argument("--devices", type=int, required=True,
                    help="per-process mesh width after the rescale")
    rs.add_argument("--processes", type=int, default=None, metavar="M",
                    help="host-process count after the rescale "
                         "(default: keep the current count)")
    rs.add_argument("job_id")

    args = p.parse_args(argv)

    if args.cmd == "analyze":
        return _analyze(args)

    if args.cmd == "session":
        return _session(args)

    if args.cmd == "lint":
        from flink_tpu.analysis.pylints import LINT_PLANES, lint_paths

        if args.plane is not None \
                and args.plane not in set(LINT_PLANES.values()):
            # an unknown plane silently reporting NOTHING would leave
            # a CI gate green while checking nothing — usage error
            print(f"error: unknown lint plane {args.plane!r} "
                  f"(known: {', '.join(sorted(set(LINT_PLANES.values())))})",
                  file=sys.stderr)
            return 2
        try:
            findings = lint_paths(args.paths or None)
        except ValueError as e:  # typo'd path: fail loudly, not green
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.plane is not None:
            findings = [f for f in findings
                        if LINT_PLANES.get(f.rule) == args.plane]
        _print_findings(findings, as_json=args.json)
        return 1 if findings else 0

    if args.cmd == "fsck":
        from flink_tpu.fsck import main as fsck_main

        return fsck_main(args)

    if args.cmd == "log":
        import os

        from flink_tpu.fs import get_filesystem
        from flink_tpu.log.topic import LogError, describe_topic

        # path errors are exit 2 (the analyze/lint contract: a typo'd
        # TOPIC_DIR — or an unregistered scheme — must not read like
        # corrupt topic state)
        try:
            missing = not get_filesystem(args.topic).exists(
                os.path.join(args.topic, "meta.json"))
        except ValueError as e:  # no filesystem for the scheme
            print(f"error: {e}", file=sys.stderr)
            return 2
        if missing:
            print(f"error: no such log topic: {args.topic!r} "
                  "(no meta.json)", file=sys.stderr)
            return 2
        try:
            out = {}
            if args.compact or args.retain:
                from flink_tpu.config import Configuration
                from flink_tpu.log.bus import TopicMaintenance
                from flink_tpu.log.cleaner import check_manual_maintenance

                # a live background cleaner service owns maintenance
                # on this topic — a manual pass must refuse loudly
                # (exit 1) instead of fighting it for the maintenance
                # lock mid-cadence
                check_manual_maintenance(args.topic)
                config = Configuration(_parse_conf(args.conf))
                if args.compact:
                    out["compaction"] = (
                        TopicMaintenance.compact_from_config(
                            config, args.topic))
                if args.retain:
                    out["retention"] = (
                        TopicMaintenance.retain_from_config(
                            config, args.topic))
            print(json.dumps({**out,
                              **describe_topic(args.topic)}))
        except LogError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.cmd == "run":
        job_id = args.job_id or f"job-{uuid.uuid4().hex[:8]}"
        conf = _parse_conf(args.conf)
        if args.runtime_mode:
            conf["execution.runtime-mode"] = args.runtime_mode
        if args.local:
            return _run_local(args.entry, conf, job_id)
        if args.session or args.ha_dir:
            return _run_attached(args.session, args.entry, conf, job_id,
                                 ha_dir=args.ha_dir)
        if not args.coordinator:
            raise SystemExit(
                "run needs --coordinator, --session, or --local")
        c = _coord_client(args.coordinator)
        try:
            blobs = []
            for path in args.py_file:
                import base64
                import os

                with open(path, "rb") as f:
                    data = f.read()
                r = c.call("put_blob",
                           data_b64=base64.b64encode(data).decode())
                blobs.append({"name": os.path.basename(path),
                              "digest": r["digest"]})
            resp = c.call("submit_job", job_id=job_id, entry=args.entry,
                          config=conf, py_blobs=blobs)
        finally:
            c.close()
        print(json.dumps({"job_id": job_id, **resp}))
        return 0

    c = _coord_client(args.coordinator)
    try:
        if args.cmd == "list":
            resp = c.call("list_jobs")
        elif args.cmd == "runners":
            resp = c.call("list_runners")
        elif args.cmd == "status":
            resp = c.call("job_status", job_id=args.job_id)
        elif args.cmd == "cancel":
            resp = c.call("cancel_job", job_id=args.job_id)
        elif args.cmd == "savepoint":
            resp = c.call("trigger_savepoint", job_id=args.job_id)
        elif args.cmd == "rescale":
            resp = c.call("rescale_job", job_id=args.job_id,
                          devices=args.devices,
                          processes=args.processes)
        else:  # pragma: no cover
            raise SystemExit(f"unknown command {args.cmd}")
    finally:
        c.close()
    print(json.dumps(resp))
    return 0 if resp.get("ok", True) else 1


if __name__ == "__main__":
    sys.exit(main())
