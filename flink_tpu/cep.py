"""CEP — complex event processing (pattern matching on keyed streams).

ref: flink-libraries/flink-cep (Pattern.begin/next/followedBy/where/
within → NFACompiler → CepOperator keeping per-key NFA state +
partial-match buffers in keyed state).

TPU-first redesign: the reference walks one NFA per key per RECORD.
Here the per-key automaton state is COLUMNS over key slots (current
stage, window-start ts, per-stage match timestamps), and a microbatch
is processed by WITHIN-KEY RANK: sort by (key, ts), then step r
advances EVERY key's automaton on its r-th event of the batch at once —
the sequential dependence lives only along each key's own event chain,
so the loop length is the longest per-key run in the batch while each
step is one vectorized transition over all keys.

Supported semantics (a deterministic, documented subset of the
reference's full NFA):
- linear patterns: ``begin(a).next(b)`` (STRICT contiguity — the very
  next event of that key must match or the partial resets) and
  ``followed_by`` (RELAXED — non-matching events in between are
  skipped), with vectorized ``where`` predicates per stage;
- ``within(ms)``: a partial older than the window resets (the event
  that broke it may immediately start a new partial);
- after-match skipping: SKIP_PAST_LAST_EVENT (default — each event
  belongs to at most one match, matches never overlap) or
  ``after_match("NO_SKIP")`` — overlapping matches enumerated from a
  BOUNDED per-key partial buffer (``max_partials`` columns, loud
  overflow; linear patterns only — quantified patterns with NO_SKIP
  would need the reference's exponential SharedBuffer branch
  enumeration and are refused at build);
- default mode keeps one active partial per key (greedy earliest): no
  simultaneous alternative partials. A failed strict transition
  re-tests the breaking event against stage 0.

Matches emit one row per completed pattern: key, ``<stage>_ts`` per
stage, and the match's start/end timestamps.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.state.keyed import KeyDirectory, account_full_drop
from flink_tpu.time.watermarks import LONG_MIN


@dataclasses.dataclass(frozen=True)
class _Stage:
    name: str
    where: Optional[Callable[[Dict[str, np.ndarray]], np.ndarray]]
    strict: bool  # True = next() contiguity; False = followed_by()
    times: int = 1        # expand into this many copies (times(n))
    loop: bool = False    # oneOrMore: greedy unbounded repetition
    optional: bool = False  # may be skipped when the NEXT stage matches


class Pattern:
    """Fluent pattern builder (ref: cep/pattern/Pattern.java)."""

    def __init__(self, stages: Tuple[_Stage, ...],
                 within_ms: Optional[int] = None,
                 after_match_mode: str = "SKIP_PAST_LAST_EVENT"):
        self._stages = stages
        self.within_ms = within_ms
        self.after_match_mode = after_match_mode

    @classmethod
    def begin(cls, name: str) -> "Pattern":
        return cls((_Stage(name, None, strict=False),))

    def where(self, pred: Callable[[Dict[str, np.ndarray]], np.ndarray]) -> "Pattern":
        """Vectorized predicate over the batch's field arrays → (B,)
        bool. Applies to the most recent stage."""
        last = self._stages[-1]
        return Pattern(self._stages[:-1]
                       + (_Stage(last.name, pred, last.strict),),
                       self.within_ms, self.after_match_mode)

    def next(self, name: str) -> "Pattern":
        """STRICT contiguity: the key's immediately-next event."""
        return Pattern(self._stages + (_Stage(name, None, strict=True),),
                       self.within_ms, self.after_match_mode)

    def followed_by(self, name: str) -> "Pattern":
        """RELAXED contiguity: later event, intervening ones skipped."""
        return Pattern(self._stages + (_Stage(name, None, strict=False),),
                       self.within_ms, self.after_match_mode)

    def within(self, ms: int) -> "Pattern":
        return Pattern(self._stages, int(ms), self.after_match_mode)

    def after_match(self, mode: str) -> "Pattern":
        """After-match skip strategy (ref: cep/nfa/aftermatch/
        AfterMatchSkipStrategy): SKIP_PAST_LAST_EVENT (default —
        deterministic, each event in at most one match) or NO_SKIP
        (the reference default — overlapping matches enumerated from a
        BOUNDED per-key partial buffer, cap + loud overflow; linear
        patterns only — quantifiers with NO_SKIP are refused at build
        because the branch enumeration is exactly the exponential
        SharedBuffer this design trades away)."""
        if mode not in ("SKIP_PAST_LAST_EVENT", "NO_SKIP"):
            raise ValueError(
                f"after_match mode {mode!r}: supported modes are "
                "SKIP_PAST_LAST_EVENT and NO_SKIP")
        return Pattern(self._stages, self.within_ms, mode)

    # -- quantifiers (ref: cep/pattern/Quantifier.java) -----------------

    def times(self, n: int) -> "Pattern":
        """The most recent stage must occur exactly ``n`` times.
        Repetitions inherit the stage's contiguity (next → strict
        consecutive runs; followed_by → gaps allowed) and expand into
        ``n`` engine stages at build time, so the vectorized rank-step
        engine runs them unchanged. Match rows carry
        ``<name>_1_ts .. <name>_n_ts``."""
        if n < 1:
            raise ValueError(f"times({n}): n must be >= 1")
        last = self._stages[-1]
        if last.loop or last.optional:
            raise ValueError(
                f"stage {last.name!r} already has a quantifier")
        return Pattern(self._stages[:-1]
                       + (dataclasses.replace(last, times=n),),
                       self.within_ms, self.after_match_mode)

    def one_or_more(self) -> "Pattern":
        """GREEDY unbounded repetition of the most recent stage
        (ref: Pattern.oneOrMore, greedy + relaxed internal contiguity).
        Deterministic subset: the loop absorbs every matching event
        until an event matches the FOLLOWING stage (which terminates
        the match), so the pattern must continue past it — a trailing
        oneOrMore would need the reference's exponential partial-match
        buffers to decide when to emit. Match rows carry
        ``<name>_ts`` (first), ``<name>_last_ts`` and ``<name>_count``."""
        last = self._stages[-1]
        if last.strict:
            raise ValueError(
                "one_or_more() requires relaxed contiguity — use "
                "followed_by(), not next(), for the repeated stage")
        if last.times != 1 or last.optional:
            raise ValueError(
                f"stage {last.name!r} already has a quantifier")
        return Pattern(self._stages[:-1]
                       + (dataclasses.replace(last, loop=True),),
                       self.within_ms, self.after_match_mode)

    def optional(self) -> "Pattern":
        """The most recent stage may be absent: when an event matches
        the FOLLOWING stage while this one is pending, the automaton
        skips it (ref: Pattern.optional). Its ``<name>_ts`` column is
        -1 in matches where it was skipped."""
        last = self._stages[-1]
        if last.loop or last.times != 1:
            raise ValueError(
                f"stage {last.name!r} already has a quantifier")
        return Pattern(self._stages[:-1]
                       + (dataclasses.replace(last, optional=True),),
                       self.within_ms, self.after_match_mode)

    @property
    def stages(self) -> Tuple[_Stage, ...]:
        """Quantifier-EXPANDED engine stages + validation."""
        for s in self._stages:
            if s.where is None:
                raise ValueError(f"stage {s.name!r} has no where()")
        out: List[_Stage] = []
        for i, s in enumerate(self._stages):
            is_last = i == len(self._stages) - 1
            if s.loop and is_last:
                raise ValueError(
                    "a trailing one_or_more() cannot decide when the "
                    "match ends in the deterministic engine — add a "
                    "terminating stage after it")
            if s.optional and is_last:
                raise ValueError(
                    "a trailing optional() stage is not supported — "
                    "the match would be ambiguous (with-or-without)")
            if s.optional and i == 0:
                raise ValueError(
                    "optional() on the first stage is not supported — "
                    "the match start would be undefined when skipped")
            if (s.loop or s.optional) and not is_last \
                    and self._stages[i + 1].strict:
                raise ValueError(
                    f"stage after quantified {s.name!r} must use "
                    "followed_by() (strict next() after a variable-"
                    "length stage is ambiguous)")
            if s.times == 1:
                out.append(s)
            else:
                for rep in range(1, s.times + 1):
                    out.append(dataclasses.replace(
                        s, name=f"{s.name}_{rep}", times=1,
                        # first repetition keeps the stage's contiguity
                        # vs its predecessor; the rest repeat with the
                        # stage's own contiguity between repetitions
                        strict=s.strict))
        if sum(1 for s in out if s.loop) > 1:
            raise ValueError(
                "at most one one_or_more() stage per pattern (the "
                "engine keeps a single loop counter per key)")
        return tuple(out)


class CepOperator:
    """Keyed pattern-matching operator (ref: cep/operator/CepOperator).
    Driver protocol mirrors KeyedProcessOperator: process_batch ingests,
    take_fired returns match rows."""

    def __init__(self, pattern: Pattern, *, num_shards: int = 128,
                 slots_per_shard: int = 1024) -> None:
        self.pattern = pattern
        self.stages = pattern.stages
        self.S = len(self.stages)
        if self.S < 1:
            raise ValueError("pattern needs at least one stage")
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        cap = num_shards * slots_per_shard
        self.stage = np.zeros(cap, np.int32)        # next stage to match
        self.stage_ts = np.zeros((cap, self.S), np.int64)
        # quantifier flags over EXPANDED stages + loop state (at most
        # one one_or_more stage per pattern — validated at build)
        self._is_loop = np.array([s.loop for s in self.stages], bool)
        self._is_opt = np.array([s.optional for s in self.stages], bool)
        self._loop_idx = (int(np.nonzero(self._is_loop)[0][0])
                          if self._is_loop.any() else -1)
        self.loop_cnt = np.zeros(cap, np.int32)
        self.loop_last = np.zeros(cap, np.int64)
        # highest event ts processed per key: the automaton consumes
        # each key's events in time order WITHIN a batch; an event
        # arriving in a later batch but timestamped before this frontier
        # cannot be sequenced (no cross-batch buffering in v1) — it is
        # dropped WITH accounting (late_records), never silently woven
        # in out of order (which could emit matches whose stage
        # timestamps run backward)
        self._last_ts = np.full(cap, np.iinfo(np.int64).min, np.int64)
        self.watermark = LONG_MIN
        self.late_records = 0
        self.records_dropped_full = 0
        self.state_version = 0
        self._matches: List[Dict[str, np.ndarray]] = []
        # NO_SKIP: a BOUNDED partial-match buffer per key — the
        # SharedBuffer role (ref: cep/nfa/sharedbuffer) capped at
        # ``max_partials`` columns with loud overflow. Linear patterns
        # only: quantifiers would need branch enumeration (the
        # exponential part this design refuses).
        self.no_skip = pattern.after_match_mode == "NO_SKIP"
        self.max_partials = 8
        if self.no_skip:
            if self._is_loop.any() or self._is_opt.any():
                raise NotImplementedError(
                    "after_match('NO_SKIP') supports linear patterns "
                    "(next/followed_by/times) only; one_or_more and "
                    "optional need the exponential branch enumeration "
                    "of the reference's SharedBuffer — use the default "
                    "SKIP_PAST_LAST_EVENT for quantified patterns")
            P = self.max_partials
            self.p_stage = np.full((cap, P), -1, np.int8)
            self.p_ts = np.zeros((cap, P, self.S), np.int64)

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = (np.ones(len(ts), bool) if valid is None
                 else np.asarray(valid, bool))
        idx = np.nonzero(valid)[0]
        if len(idx) == 0:
            return
        slots = self.directory.assign(keys[idx])
        bad = slots < 0
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            idx, slots = idx[~bad], slots[~bad]
        if len(idx) == 0:
            return

        # cross-batch order: drop events behind the key's frontier
        fresh = ts[idx] >= self._last_ts[slots]
        if not fresh.all():
            self.late_records += int((~fresh).sum())
            idx, slots = idx[fresh], slots[fresh]
            if len(idx) == 0:
                return

        # pre-evaluate every stage predicate over the whole batch ONCE
        # (vectorized; the rank loop below only gathers bits)
        sub = {k: np.asarray(v)[idx] for k, v in data.items()}
        preds = np.stack([np.asarray(st.where(sub), bool)
                          for st in self.stages])      # (S, n)

        # order by (key, ts); within-key rank = position in its run
        order = np.lexsort((ts[idx], keys[idx]))
        sl = slots[order].astype(np.int64)
        tt = ts[idx][order]
        kk = keys[idx][order]
        pr = preds[:, order]                            # (S, n)
        run_start = np.empty(len(sl), bool)
        run_start[0] = True
        run_start[1:] = kk[1:] != kk[:-1]
        rank = np.arange(len(sl)) - np.maximum.accumulate(
            np.where(run_start, np.arange(len(sl)), 0))
        max_rank = int(rank.max()) + 1

        if self.no_skip:
            self._steps_no_skip(sl, tt, kk, pr, rank, max_rank)
            return

        within = self.pattern.within_ms
        strict = np.array([s.strict for s in self.stages], bool)
        is_loop, is_opt = self._is_loop, self._is_opt
        for r in range(max_rank):
            m = rank == r                    # one event per key this step
            s_r = sl[m]
            t_r = tt[m]
            p_r = pr[:, m]                   # (S, k)
            k = len(s_r)
            ar = np.arange(k)
            cur = self.stage[s_r]            # (k,) next stage to match

            # within-window expiry: partial too old resets to stage 0
            if within is not None:
                expired = (cur > 0) & (t_r - self.stage_ts[s_r, 0] > within)
                cur = np.where(expired, 0, cur)
                if self._loop_idx >= 0:
                    self.loop_cnt[s_r[expired]] = 0

            curc = np.minimum(cur, self.S - 1)
            hit_cur = p_r[curc, ar]
            nxtc = np.minimum(cur + 1, self.S - 1)
            has_next = cur + 1 < self.S
            hit_next = p_r[nxtc, ar] & has_next
            lp = is_loop[curc] & (cur < self.S)
            op_ = is_opt[curc] & (cur < self.S)
            in_loop = lp & (self.loop_cnt[s_r] > 0)

            # decision precedence (greedy loop first):
            # A. loop enter/continue: stay, count, track first/last ts
            a_loop = lp & hit_cur
            # B. loop exit: the FOLLOWING stage's event terminates it
            b_exit = in_loop & ~hit_cur & hit_next
            # C. optional skip: next stage's event while optional pends
            c_skip = op_ & ~hit_cur & hit_next
            # D. plain advance
            d_adv = ~lp & ~c_skip & hit_cur
            # E. strict miss -> partial dies (breaking event re-tests
            #    stage 0)
            miss_strict = (~a_loop & ~b_exit & ~c_skip & ~d_adv
                           & ~hit_cur & strict[curc] & (cur > 0))
            restart = miss_strict & p_r[0, ar]

            new_stage = np.where(
                a_loop, cur,
                np.where(b_exit | c_skip, cur + 2,
                         np.where(d_adv, cur + 1,
                                  np.where(miss_strict,
                                           np.where(restart, 1, 0),
                                           cur))))

            # timestamp bookkeeping
            enter_loop = a_loop & ~in_loop
            if self._loop_idx >= 0:
                self.loop_cnt[s_r[enter_loop]] = 1
                cont = a_loop & in_loop
                self.loop_cnt[s_r[cont]] += 1
                self.loop_last[s_r[a_loop]] = t_r[a_loop]
            # first occurrence of a stage writes its ts: plain advances
            # at cur, loop entries at cur, exits/skips at cur+1
            w_cur = d_adv | enter_loop | restart
            st_cur = np.where(restart, 0, cur)
            self.stage_ts[s_r[w_cur], st_cur[w_cur]] = t_r[w_cur]
            w_nxt = b_exit | c_skip
            self.stage_ts[s_r[w_nxt], np.minimum(cur[w_nxt] + 1,
                                                 self.S - 1)] = t_r[w_nxt]
            # a skipped optional stage reads -1 in the match row
            self.stage_ts[s_r[c_skip], curc[c_skip]] = -1

            done = new_stage >= self.S
            if done.any():
                d = np.nonzero(done)[0]
                row = {"key": kk[m][d],
                       "match_start": self.stage_ts[s_r[d], 0].copy(),
                       "match_end": t_r[d].copy()}
                for si, stg in enumerate(self.stages):
                    row[f"{stg.name}_ts"] = self.stage_ts[s_r[d], si].copy()
                if self._loop_idx >= 0:
                    ln = self.stages[self._loop_idx].name
                    row[f"{ln}_last_ts"] = self.loop_last[s_r[d]].copy()
                    row[f"{ln}_count"] = self.loop_cnt[s_r[d]].copy()
                    self.loop_cnt[s_r[d]] = 0
                self._matches.append(row)
                new_stage = np.where(done, 0, new_stage)  # SKIP_PAST_LAST

            self.stage[s_r] = new_stage.astype(np.int32)
            self._last_ts[s_r] = t_r

    def _steps_no_skip(self, sl, tt, kk, pr, rank, max_rank) -> None:
        """NO_SKIP rank-step engine: every key advances ALL its live
        partials on each event at once (vectorized over keys × the
        bounded partial axis), and an event matching stage 0 also
        SPAWNS a fresh partial — overlapping matches enumerate across
        partials. Per partial the take is greedy (the operator's
        documented determinism trade); across partials the overlap
        semantics match the reference's NO_SKIP for linear patterns.

        BATCH ATOMICITY: the partial-buffer overflow error must leave
        the operator exactly as it was before the batch — earlier rank
        steps have already advanced partials and appended matches by the
        time a later rank overflows, and a caller that catches the error
        (to fail over through restore, or to drop the batch) must not
        observe half-applied state or double-emitted matches on retry.
        The touched rows (only the batch's key slots) are snapshotted on
        entry and rolled back on the error path — an exact guarantee a
        pre-scan cannot give, since slot liberation (expiry, completion,
        strict death) during the batch feeds back into overflow. One
        deliberate residue: key-directory slots assigned for the batch's
        new keys (in process_batch, before this point) stay assigned —
        the key→slot mapping is idempotent and carries no match state,
        the slot is reused if the key returns, and a restore-from-
        checkpoint rebuilds the directory anyway; only keys never seen
        again leave an empty slot behind."""
        touched = np.unique(sl)
        bak = (self.p_stage[touched].copy(), self.p_ts[touched].copy(),
               self._last_ts[touched].copy(), len(self._matches))
        try:
            self._steps_no_skip_inner(sl, tt, kk, pr, rank, max_rank)
        except Exception:
            self.p_stage[touched], self.p_ts[touched] = bak[0], bak[1]
            self._last_ts[touched] = bak[2]
            del self._matches[bak[3]:]
            raise

    def _steps_no_skip_inner(self, sl, tt, kk, pr, rank,
                             max_rank) -> None:
        S, P = self.S, self.max_partials
        within = self.pattern.within_ms
        strict = np.array([s.strict for s in self.stages], bool)
        for r in range(max_rank):
            m = rank == r
            s_r = sl[m]
            t_r = tt[m]
            p_r = pr[:, m]                     # (S, k)
            k = len(s_r)
            ar = np.arange(k)
            st = self.p_stage[s_r].astype(np.int32)   # (k, P)
            act = st >= 0
            if within is not None and act.any():
                exp = act & (t_r[:, None] - self.p_ts[s_r, :, 0] > within)
                st = np.where(exp, -1, st)
                act = st >= 0
            stc = np.clip(st, 0, S - 1)
            hit = p_r.T[ar[:, None], stc] & act       # (k, P)
            died = act & ~hit & strict[stc] & (st > 0)
            adv = act & hit
            ii, pp = np.nonzero(adv)
            if len(ii):
                self.p_ts[s_r[ii], pp, stc[ii, pp]] = t_r[ii]
            st = np.where(adv, st + 1, np.where(died, -1, st))
            compl = st >= S
            if compl.any():
                ci, cp = np.nonzero(compl)
                row = {"key": kk[m][ci],
                       "match_start": self.p_ts[s_r[ci], cp, 0].copy(),
                       "match_end": t_r[ci].copy()}
                for si, stg in enumerate(self.stages):
                    row[f"{stg.name}_ts"] = self.p_ts[
                        s_r[ci], cp, si].copy()
                self._matches.append(row)
                st = np.where(compl, -1, st)
            # spawn: stage-0 match starts a NEW partial (even when the
            # same event extended others — the overlap contract)
            want = p_r[0]
            if want.any():
                free = st < 0
                has_free = free.any(axis=1)
                over = want & ~has_free
                if over.any():
                    raise RuntimeError(
                        f"CEP NO_SKIP partial-buffer overflow: a key "
                        f"exceeded {P} simultaneous partial matches "
                        "(cep max_partials); narrow the begin-stage "
                        "predicate, add within(), or use "
                        "SKIP_PAST_LAST_EVENT")
                ff = np.argmax(free, axis=1)
                wi = np.nonzero(want)[0]
                if S == 1:
                    self._matches.append({
                        "key": kk[m][wi],
                        "match_start": t_r[wi].copy(),
                        "match_end": t_r[wi].copy(),
                        f"{self.stages[0].name}_ts": t_r[wi].copy()})
                else:
                    st[wi, ff[wi]] = 1
                    self.p_ts[s_r[wi], ff[wi], 0] = t_r[wi]
            self.p_stage[s_r] = st.astype(np.int8)
            self._last_ts[s_r] = t_r

    def take_fired(self):
        from flink_tpu.ops.window import FiredWindows

        if not self._matches:
            return None
        parts = self._matches
        self._matches = []
        out = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
        out["__ts__"] = out["match_end"].astype(np.int64)
        return FiredWindows(data=out)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm > self.watermark:
            self.watermark = wm
        return FiredWindows(data={"__ts__": np.zeros(0, np.int64)})

    def final_watermark(self) -> int:
        return self.watermark if self.watermark != LONG_MIN else 0

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "cep",
            "directory": self.directory.snapshot(),
            "stage": self.stage.copy(),
            "stage_ts": self.stage_ts.copy(),
            "loop_cnt": self.loop_cnt.copy(),
            "loop_last": self.loop_last.copy(),
            "watermark": self.watermark,
            "late_records": self.late_records,
            "records_dropped_full": self.records_dropped_full,
            "last_ts": self._last_ts.copy(),
            "p_stage": (self.p_stage.copy() if self.no_skip else None),
            "p_ts": (self.p_ts.copy() if self.no_skip else None),
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.stage = np.array(snap["stage"])
        self.stage_ts = np.array(snap["stage_ts"])
        if snap.get("loop_cnt") is not None:
            self.loop_cnt = np.array(snap["loop_cnt"])
            self.loop_last = np.array(snap["loop_last"])
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self.records_dropped_full = snap["records_dropped_full"]
        self._last_ts = np.array(snap["last_ts"])
        if self.no_skip and snap.get("p_stage") is not None:
            self.p_stage = np.array(snap["p_stage"])
            self.p_ts = np.array(snap["p_ts"])
        self._matches = []


class CEP:
    """Entry point (ref: cep/CEP.java): ``CEP.pattern(keyed_stream,
    pattern)`` → DataStream of match rows."""

    @staticmethod
    def pattern(keyed_stream, pattern: Pattern, name: str = "cep"):
        from flink_tpu.graph.transformations import CepTransformation

        kt = keyed_stream.transform
        t = CepTransformation(name, (kt,), pattern=pattern,
                              key_field=kt.key_field)
        keyed_stream.env._register(t)
        from flink_tpu.api.datastream import DataStream

        return DataStream(keyed_stream.env, t)
